"""Auto-generated unary activation layers (reference layers/ops.py pattern:
`__activations_noattr__` generated from the op registry)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "rsqrt", "abs",
    "ceil", "floor", "round", "cos", "sin", "tan", "acos", "asin", "atan",
    "sinh", "cosh", "square", "reciprocal", "softplus", "softsign",
    "logsigmoid", "erf", "mish", "sign", "silu", "log2", "log10", "log1p",
]


def _make_unary(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


for _op in _UNARY_OPS:
    globals()[_op] = _make_unary(_op)


def gelu(x, approximate=False):
    helper = LayerHelper("gelu")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="gelu",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"approximate": approximate},
    )
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="leaky_relu",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"alpha": alpha},
    )
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper("elu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="elu", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"alpha": alpha}
    )
    return out


def relu6(x, threshold=6.0, name=None):
    helper = LayerHelper("relu6", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="relu6",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"threshold": threshold},
    )
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    helper = LayerHelper("hard_sigmoid", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="hard_sigmoid",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"slope": slope, "offset": offset},
    )
    return out


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    helper = LayerHelper("hard_swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="hard_swish",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"threshold": threshold, "scale": scale, "offset": offset},
    )
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper("swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="swish", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"beta": beta}
    )
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pow", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"factor": factor}
    )
    return out


def soft_shrink(x, alpha=0.5):
    helper = LayerHelper("soft_shrink")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="soft_shrink",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"lambda": alpha},
    )
    return out


def hard_shrink(x, threshold=0.5):
    helper = LayerHelper("hard_shrink")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="hard_shrink",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"threshold": threshold},
    )
    return out


def thresholded_relu(x, threshold=1.0):
    helper = LayerHelper("thresholded_relu")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="thresholded_relu",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"threshold": threshold},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="maxout",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"groups": groups},
    )
    return out
