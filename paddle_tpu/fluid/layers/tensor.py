"""Tensor-creation / manipulation layers.

Parity surface: python/paddle/fluid/layers/tensor.py in the reference.
"""
from __future__ import annotations

import numpy as np

from .. import framework, unique_name
from ..dtypes import convert_dtype
from ..framework import Variable
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True):
    """fluid.layers.data — prepends a -1 batch dim unless told otherwise."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    shape = [-1 if s is None else int(s) for s in shape]
    block = framework.default_main_program().global_block()
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        is_data=True,
        stop_gradient=True,
    )


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable_for_type_inference(dtype=dtype)


def create_parameter(
    shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None
):
    helper = LayerHelper("create_parameter", name=name, param_attr=attr)
    attr = helper.param_attr
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        persistable=persistable, shape=tuple(shape), dtype=convert_dtype(dtype)
    )
    helper.set_variable_initializer(var, ConstantInitializer(value))
    if not persistable:
        # non-persistable globals still need a runtime value
        helper.main_program.global_block().append_op(
            type="fill_constant",
            outputs={"Out": [var]},
            attrs={"shape": list(shape), "dtype": var.dtype, "value": float(value)},
        )
    return var


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_dtype(dtype), "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": convert_dtype(dtype),
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"out_dtype": convert_dtype(dtype)},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(
        type="concat",
        inputs={"X": input},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(input.shape),
                "dtype": convert_dtype(input.dtype),
                "values": input.flatten().tolist(),
            },
        )
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="assign", inputs={"X": [input]}, outputs={"Out": [output]})
    return output


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="fill_any_like",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"value": 1.0},
    )
    return out


def full_like(x, fill_value, dtype=None):
    helper = LayerHelper("full_like")
    out = helper.create_variable_for_type_inference(dtype=dtype or x.dtype)
    attrs = {"value": float(fill_value)}
    if dtype is not None:
        attrs["dtype"] = convert_dtype(dtype)
    helper.append_op(
        type="fill_any_like", inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
    )
    return out


def range(start, end, step, dtype="int64"):
    helper = LayerHelper("range")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="range",
        outputs={"Out": [out]},
        attrs={
            "start": float(start),
            "end": float(end),
            "step": float(step),
            "dtype": convert_dtype(dtype),
        },
    )
    out.stop_gradient = True
    return out


arange = range


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="linspace",
        outputs={"Out": [out]},
        attrs={
            "start": float(start),
            "stop": float(stop),
            "num": int(num),
            "dtype": convert_dtype(dtype),
        },
    )
    return out


def eye(num_rows, num_columns=None, dtype="float32"):
    helper = LayerHelper("eye")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="eye",
        outputs={"Out": [out]},
        attrs={
            "num_rows": int(num_rows),
            "num_columns": int(num_columns or num_rows),
            "dtype": convert_dtype(dtype),
        },
    )
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(dtype=diagonal.dtype)
    helper.append_op(
        type="diag_v2", inputs={"X": [diagonal]}, outputs={"Out": [out]}, attrs={}
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("argmax")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="arg_max",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    out.stop_gradient = True
    return out


def argmin(x, axis=0):
    helper = LayerHelper("argmin")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="arg_min",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    out.stop_gradient = True
    return out


def argsort(x, axis=-1, descending=False):
    helper = LayerHelper("argsort")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    ids = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="argsort",
        inputs={"X": [x]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis, "descending": descending},
    )
    return out, ids


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op(
        type="flip",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": list(axis)},
    )
    return out


def has_inf(x):
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isinf", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_nan(x):
    helper = LayerHelper("has_nan")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isnan", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


# -- comparisons (reference layers/control_flow.py less_than etc.) ----------


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(
        type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    cond.stop_gradient = True
    return cond


def less_than(x, y, cond=None, name=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None, name=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None, name=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None, name=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None, name=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None, name=None):
    return _compare("not_equal", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _compare("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _compare("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _compare("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def increment(x, value=1.0, in_place=True):
    """reference layers/control_flow.py increment — in-place step bump."""
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"step": float(value)}
    )
    return out


def isfinite_v2(x, name=None):
    """Elementwise finite test (op isfinite_v2); reference isfinite reduces."""
    helper = LayerHelper("isfinite_v2")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isfinite_v2", inputs={"X": [x]}, outputs={"Out": [out]})
    out.stop_gradient = True
    return out


# ---------------------------------------------------------------------------
# thin wrappers for registered ops that the 2.0 tensor namespace re-exports
# (reference python/paddle/tensor/* emits the same op types)
# ---------------------------------------------------------------------------


def _unary_layer(op_type, x, attrs=None, out_dtype=None, in_slot="X",
                 out_slot="Out"):
    from ..layer_helper import emit_op

    return emit_op(op_type, {in_slot: [x]}, attrs, out_slots=(out_slot,),
                   out_dtype=out_dtype)


def tile(x, repeat_times, name=None):
    return _unary_layer("tile", x, {"repeat_times": list(repeat_times)})


def flip(x, axis, name=None):
    axis = [axis] if isinstance(axis, int) else list(axis)
    return _unary_layer("flip", x, {"axis": axis})


def roll(x, shifts, axis=None, name=None):
    shifts = [shifts] if isinstance(shifts, int) else list(shifts)
    if axis is not None:
        axis = [axis] if isinstance(axis, int) else list(axis)
    return _unary_layer("roll", x, {"shifts": shifts, "axis": axis or []})


def tril(x, diagonal=0, name=None):
    return _unary_layer("tril_triu", x, {"lower": True, "diagonal": diagonal})


def triu(x, diagonal=0, name=None):
    return _unary_layer("tril_triu", x, {"lower": False, "diagonal": diagonal})


def meshgrid(*args, name=None):
    inputs = list(args[0]) if len(args) == 1 and isinstance(args[0], (list, tuple)) else list(args)
    helper = LayerHelper("meshgrid")
    outs = [
        helper.create_variable_for_type_inference(inputs[0].dtype)
        for _ in inputs
    ]
    helper.append_op(
        type="meshgrid", inputs={"X": inputs}, outputs={"Out": outs}, attrs={}
    )
    return outs


def index_select(x, index, axis=0, name=None):
    helper = LayerHelper("index_select")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="index_select", inputs={"X": [x], "Index": [index]},
        outputs={"Out": [out]}, attrs={"dim": axis},
    )
    return out


def take_along_axis(x, indices, axis, name=None):
    helper = LayerHelper("take_along_axis")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="take_along_axis", inputs={"Input": [x], "Index": [indices]},
        outputs={"Result": [out]}, attrs={"Axis": axis},
    )
    return out


def unbind(x, axis=0, name=None):
    helper = LayerHelper("unbind")
    n = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(n)]
    helper.append_op(
        type="unbind", inputs={"X": [x]}, outputs={"Out": outs},
        attrs={"axis": axis},
    )
    return outs


def _binary_layer(op_type, x, y, attrs=None, x_slot="X", y_slot="Y"):
    from ..layer_helper import emit_op

    return emit_op(op_type, {x_slot: [x], y_slot: [y]}, attrs)


def dot(x, y, name=None):
    return _binary_layer("dot", x, y)


def kron(x, y, name=None):
    return _binary_layer("kron", x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    helper = LayerHelper("addmm")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="addmm", inputs={"Input": [input], "X": [x], "Y": [y]},
        outputs={"Out": [out]}, attrs={"Alpha": alpha, "Beta": beta},
    )
    return out


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _unary_layer(
        "trace", x, {"offset": offset, "axis1": axis1, "axis2": axis2},
        in_slot="Input",
    )


def cholesky(x, upper=False, name=None):
    return _unary_layer("cholesky", x, {"upper": upper})


def inverse(x, name=None):
    return _unary_layer("inverse", x, in_slot="Input", out_slot="Output")


def matrix_power(x, n, name=None):
    return _unary_layer("matrix_power", x, {"n": n})


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    helper = LayerHelper("allclose")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        type="allclose", inputs={"Input": [x], "Other": [y]},
        outputs={"Out": [out]},
        attrs={"rtol": rtol, "atol": atol, "equal_nan": equal_nan},
    )
    return out


def isnan_v2(x, name=None):
    return _unary_layer("isnan_v2", x, out_dtype="bool")


def isinf_v2(x, name=None):
    return _unary_layer("isinf_v2", x, out_dtype="bool")
