"""Detection layers (reference python/paddle/fluid/layers/detection.py —
the subset whose ops are implemented: iou_similarity, box_coder,
prior_box, yolo_box, roi_align)."""
from __future__ import annotations

from ..layer_helper import LayerHelper, emit_op

__all__ = ["iou_similarity", "box_coder", "prior_box", "yolo_box",
           "roi_align"]


def iou_similarity(x, y, box_normalized=True, name=None):
    return emit_op("iou_similarity", {"X": [x], "Y": [y]},
                   {"box_normalized": box_normalized})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            attrs["variance"] = [float(v) for v in prior_box_var]
        else:
            ins["PriorBoxVar"] = [prior_box_var]
    return emit_op("box_coder", ins, attrs, out_slots=("OutputBox",))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    outs = emit_op(
        "prior_box", {"Input": [input], "Image": [image]},
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios), "variances": list(variance),
         "flip": flip, "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset},
        out_slots=("Boxes", "Variances"),
    )
    return outs[0], outs[1]


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.005,
             downsample_ratio=32, clip_bbox=True, name=None):
    outs = emit_op(
        "yolo_box", {"X": [x], "ImgSize": [img_size]},
        {"anchors": list(anchors), "class_num": class_num,
         "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio,
         "clip_bbox": clip_bbox},
        out_slots=("Boxes", "Scores"),
    )
    return outs[0], outs[1]


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              batch_index=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_index is not None:
        ins["BatchIndex"] = [batch_index]
    elif rois_num is not None:
        ins["RoisNum"] = [rois_num]
    return emit_op(
        "roi_align", ins,
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )
