"""Detection layers (reference python/paddle/fluid/layers/detection.py —
the subset whose ops are implemented: iou_similarity, box_coder,
prior_box, yolo_box, roi_align)."""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper, emit_op

__all__ = ["iou_similarity", "box_coder", "prior_box", "yolo_box",
           "roi_align", "anchor_generator", "density_prior_box", "box_clip",
           "generate_proposals", "rpn_target_assign",
           "retinanet_target_assign", "retinanet_detection_output",
           "collect_fpn_proposals", "distribute_fpn_proposals",
           "prroi_pool", "psroi_pool", "roi_perspective_transform",
           "deformable_conv", "deformable_roi_pooling", "yolov3_loss",
           "generate_proposal_labels", "generate_mask_labels",
           "box_decoder_and_assign", "multiclass_nms", "matrix_nms",
           "locality_aware_nms", "target_assign", "bipartite_match",
           "polygon_box_transform", "ctc_greedy_decoder", "detection_output",
           "ssd_loss", "multi_box_head"]


def iou_similarity(x, y, box_normalized=True, name=None):
    return emit_op("iou_similarity", {"X": [x], "Y": [y]},
                   {"box_normalized": box_normalized})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            attrs["variance"] = [float(v) for v in prior_box_var]
        else:
            ins["PriorBoxVar"] = [prior_box_var]
    return emit_op("box_coder", ins, attrs, out_slots=("OutputBox",))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    outs = emit_op(
        "prior_box", {"Input": [input], "Image": [image]},
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios), "variances": list(variance),
         "flip": flip, "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset},
        out_slots=("Boxes", "Variances"),
    )
    return outs[0], outs[1]


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.005,
             downsample_ratio=32, clip_bbox=True, name=None):
    outs = emit_op(
        "yolo_box", {"X": [x], "ImgSize": [img_size]},
        {"anchors": list(anchors), "class_num": class_num,
         "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio,
         "clip_bbox": clip_bbox},
        out_slots=("Boxes", "Scores"),
    )
    return outs[0], outs[1]


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              batch_index=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_index is not None:
        ins["BatchIndex"] = [batch_index]
    elif rois_num is not None:
        ins["RoisNum"] = [rois_num]
    return emit_op(
        "roi_align", ins,
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    return emit_op(
        "anchor_generator", {"Input": [input]},
        {"anchor_sizes": [float(s) for s in anchor_sizes],
         "aspect_ratios": [float(r) for r in aspect_ratios],
         "variances": [float(v) for v in variance],
         "stride": [float(s) for s in stride], "offset": float(offset)},
        out_slots=("Anchors", "Variances"),
    )


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    boxes, var = emit_op(
        "density_prior_box", {"Input": [input], "Image": [image]},
        {"densities": [int(d) for d in densities],
         "fixed_sizes": [float(s) for s in fixed_sizes],
         "fixed_ratios": [float(r) for r in fixed_ratios],
         "variances": [float(v) for v in variance], "clip": clip,
         "step_w": float(steps[0]), "step_h": float(steps[1]),
         "offset": float(offset)},
        out_slots=("Boxes", "Variances"),
    )
    if flatten_to_2d:
        from . import nn as _nn

        n = 1
        for d in boxes.shape[:-1]:
            n *= d
        boxes = _nn.reshape(boxes, [n, 4])
        var = _nn.reshape(var, [n, 4])
    return boxes, var


def box_clip(input, im_info, name=None):
    return emit_op("box_clip", {"Input": [input], "ImInfo": [im_info]},
                   out_slots=("Output",))


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=None, name=None):
    """box_clip (reference semantics) bounds the w/h delta exponent
    before exp(), e.g. np.log(1000/16)."""
    if isinstance(prior_box_var, (list, tuple)):
        attrs = {"box_var": [float(v) for v in prior_box_var]}
    else:
        attrs = {}
    if box_clip is not None:
        attrs["box_clip"] = float(box_clip)
    return emit_op(
        "box_decoder_and_assign",
        {"PriorBox": [prior_box], "TargetBox": [target_box],
         "BoxScore": [box_score]},
        attrs, out_slots=("DecodeBox", "OutputAssignBox"),
    )


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, return_index=False, rois_num=None,
                   name=None):
    """Fixed-size NMS: Out [N, keep_top_k, 6] with label=-1 padding plus
    NmsRoisNum [N] (the static-shape analog of the reference's LoD rows;
    multiclass_nms_op.cc). return_index=True additionally yields the
    selected ORIGINAL box row per detection ([N, keep_top_k, 1], -1 pads,
    matching the reference's Index output)."""
    out, index, counts = emit_op(
        "multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"score_threshold": float(score_threshold),
         "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
         "nms_threshold": float(nms_threshold),
         "background_label": int(background_label)},
        out_slots=("Out", "Index", "NmsRoisNum"),
    )
    if return_index:
        return out, index
    if rois_num is not None:
        return out, counts
    return out


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    out, counts = emit_op(
        "matrix_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"score_threshold": float(score_threshold),
         "post_threshold": float(post_threshold),
         "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
         "use_gaussian": use_gaussian, "gaussian_sigma": float(gaussian_sigma),
         "background_label": int(background_label)},
        out_slots=("Out", "RoisNum"),
    )
    return (out, counts) if return_rois_num else out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                       nms_threshold=0.3, normalized=True, nms_eta=1.0,
                       background_label=-1, name=None):
    return emit_op(
        "locality_aware_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"score_threshold": float(score_threshold),
         "nms_threshold": float(nms_threshold),
         "nms_top_k": int(nms_top_k),
         "keep_top_k": int(keep_top_k)},
        out_slots=("Out",),
    )


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    return emit_op(
        "target_assign",
        {"X": [input], "MatchIndices": [matched_indices]},
        {"mismatch_value": mismatch_value},
        out_slots=("Out", "OutWeight"),
    )


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    return emit_op(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"match_type": match_type, "dist_threshold": float(dist_threshold)},
        out_slots=("ColToRowMatchIndices", "ColToRowMatchDist"),
    )


def polygon_box_transform(input, name=None):
    return emit_op("polygon_box_transform", {"Input": [input]},
                   out_slots=("Output",))


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decode (reference ctc_greedy_decoder over
    ctc_align_op.cc): argmax per step, collapse repeats, drop blanks.
    input [B, T, C] probs (dense analog of the reference's LoD input).
    Returns (decoded [B, T] left-aligned + padded, lengths [B])."""
    from . import nn as _nn
    from . import tensor as _tensor

    ids = _tensor.argmax(input, axis=-1)
    ins = {"Input": [_tensor.cast(ids, "int32")]}
    if input_length is not None:
        ins["InputLength"] = [input_length]
    return emit_op(
        "ctc_align", ins,
        {"blank": int(blank), "padding_value": int(padding_value)},
        out_slots=("Output", "OutputLength"),
    )


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """SSD post-processing (reference detection.py detection_output):
    decode loc deltas against priors, then multiclass NMS."""
    from . import nn as _nn

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = _nn.transpose(scores, [0, 2, 1])  # [N, C, P]
    return multiclass_nms(
        decoded, scores_t, score_threshold, nms_top_k, keep_top_k,
        nms_threshold=nms_threshold, background_label=background_label)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """SSD multibox loss (reference detection.py ssd_loss) as ONE fused
    differentiable op (ops/detection2_ops.py ssd_loss): matching, target
    encoding, smooth-L1 + softmax losses, and hard negative mining run in
    a single XLA program. Dense gt contract: gt_box [N, G, 4], gt_label
    [N, G] int with -1 padding rows. Returns [N, 1]."""
    ins = {"Location": [location], "Confidence": [confidence],
           "GtBox": [gt_box], "GtLabel": [gt_label],
           "PriorBox": [prior_box]}
    if prior_box_var is not None and not isinstance(
            prior_box_var, (list, tuple)):
        ins["PriorBoxVar"] = [prior_box_var]
    attrs = {
        "background_label": int(background_label),
        "overlap_threshold": float(overlap_threshold),
        "neg_pos_ratio": float(neg_pos_ratio),
        "loc_loss_weight": float(loc_loss_weight),
        "conf_loss_weight": float(conf_loss_weight),
        "normalize": bool(normalize),
    }
    if isinstance(prior_box_var, (list, tuple)):
        attrs["box_var"] = [float(v) for v in prior_box_var]
    return emit_op("ssd_loss", ins, attrs, out_slots=("Loss",))


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD heads over multiple feature maps (reference detection.py
    multi_box_head): per-input prior boxes + conv loc/conf predictions,
    concatenated over all maps."""
    from . import nn as _nn
    from . import tensor as _tensor

    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio interpolation
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2 + 1e-9)) \
            if n_layer > 2 else 100
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[: n_layer - 1]
        max_sizes = [base_size * 0.20] + max_sizes[: n_layer - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        mins = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        maxs = [max_sizes[i]] if max_sizes else None
        box, var = prior_box(
            x, image, min_sizes=mins, max_sizes=maxs, aspect_ratios=ar,
            variance=list(variance), flip=flip, clip=clip,
            steps=[steps[i], steps[i]] if steps else [0.0, 0.0],
            offset=offset)
        num_priors = 1
        for dshape in box.shape[:-1]:
            num_priors *= dshape
        num_priors //= (x.shape[2] * x.shape[3])
        loc = _nn.conv2d(x, num_priors * 4, kernel_size, padding=pad,
                         stride=stride)
        conf = _nn.conv2d(x, num_priors * num_classes, kernel_size,
                          padding=pad, stride=stride)
        # NCHW -> [N, H*W*priors, 4|C]
        nb = x.shape[0]
        loc = _nn.reshape(_nn.transpose(loc, [0, 2, 3, 1]), [nb, -1, 4])
        conf = _nn.reshape(_nn.transpose(conf, [0, 2, 3, 1]),
                           [nb, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(_nn.reshape(box, [-1, 4]))
        vars_all.append(_nn.reshape(var, [-1, 4]))
    mbox_locs = _tensor.concat(locs, axis=1)
    mbox_confs = _tensor.concat(confs, axis=1)
    boxes = _tensor.concat(boxes_all, axis=0)
    variances = _tensor.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    """RPN proposals (reference generate_proposals_op.cc): fixed-size
    [N, post_nms_top_n, 4] outputs + valid counts (static-shape analog
    of the reference's LoD rois)."""
    rois, probs, counts = emit_op(
        "generate_proposals",
        {"Scores": [scores], "BboxDeltas": [bbox_deltas],
         "ImInfo": [im_info], "Anchors": [anchors],
         "Variances": [variances]},
        {"pre_nms_topN": int(pre_nms_top_n),
         "post_nms_topN": int(post_nms_top_n),
         "nms_thresh": float(nms_thresh), "min_size": float(min_size)},
        out_slots=("RpnRois", "RpnRoiProbs", "RpnRoisNum"),
    )
    if return_rois_num:
        return rois, probs, counts
    return rois, probs


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN training targets, dense form (reference rpn_target_assign_op.cc):
    instead of the reference's gathered LoD rows, returns full-length
    per-anchor targets + 0/1 weights — consumers multiply by the weights.
    (bbox_pred/cls_logits are accepted for API parity; selection happens
    via the returned weights rather than gather indices.)

    Returns (loc_target [N,A,4], score_label [N,A], loc_weight [N,A,1],
    score_weight [N,A,1])."""
    from .nn import _rng_salt_counter

    _rng_salt_counter[0] += 1
    label, loc, locw, scorew = emit_op(
        "rpn_target_assign",
        {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]},
        {"rpn_positive_overlap": float(rpn_positive_overlap),
         "rpn_negative_overlap": float(rpn_negative_overlap),
         "rpn_batch_size_per_im": int(rpn_batch_size_per_im),
         "rpn_fg_fraction": float(rpn_fg_fraction),
         "rng_salt": _rng_salt_counter[0]},
        out_slots=("Label", "LocTarget", "LocWeight", "ScoreWeight"),
    )
    return loc, label, locw, scorew


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None, im_info=None,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """RetinaNet targets, dense form (see rpn_target_assign): returns
    (loc_target [N,A,4], cls_label [N,A], anchor_label [N,A],
    loc_weight [N,A,1], fg_num [N])."""
    label, cls, loc, locw, fg = emit_op(
        "retinanet_target_assign",
        {"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
         "GtLabels": [gt_labels]},
        {"positive_overlap": float(positive_overlap),
         "negative_overlap": float(negative_overlap)},
        out_slots=("Label", "ClsLabel", "LocTarget", "LocWeight",
                   "ForegroundNumber"),
    )
    return loc, cls, label, locw, fg


def retinanet_detection_output(bboxes, scores, im_info, score_threshold=0.05,
                               nms_top_k=1000, keep_top_k=100,
                               nms_threshold=0.45, nms_eta=1.0):
    """RetinaNet post-processing (reference
    retinanet_detection_output_op.cc): concat per-level decoded boxes and
    scores, clip to the image, then multiclass NMS."""
    from . import tensor as _tensor
    from . import nn as _nn

    boxes_cat = _tensor.concat(list(bboxes), axis=1)   # [N, sumA, 4]
    scores_cat = _tensor.concat(list(scores), axis=1)  # [N, sumA, C]
    boxes_cat = box_clip(boxes_cat, im_info)
    scores_t = _nn.transpose(scores_cat, [0, 2, 1])
    return multiclass_nms(
        boxes_cat, scores_t, score_threshold, nms_top_k, keep_top_k,
        nms_threshold=nms_threshold, background_label=-1)


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    rois, counts = emit_op(
        "collect_fpn_proposals",
        {"MultiLevelRois": list(multi_rois),
         "MultiLevelScores": list(multi_scores)},
        {"post_nms_topN": int(post_nms_top_n)},
        out_slots=("FpnRois", "RoisNum"),
    )
    if rois_num_per_level is not None:
        return rois, counts
    return rois


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Route ROIs to FPN levels (reference distribute_fpn_proposals_op.cc).
    Dense: each level tensor keeps ALL rows with non-members zeroed (use
    the LevelMask rows to filter); RestoreIndex maps back to input order."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_levels = max_level - min_level + 1
    outs = [helper.create_variable_for_type_inference(fpn_rois.dtype)
            for _ in range(n_levels)]
    mask = helper.create_variable_for_type_inference(fpn_rois.dtype)
    restore = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="distribute_fpn_proposals",
        inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": outs, "LevelMask": [mask],
                 "RestoreIndex": [restore]},
        attrs={"min_level": int(min_level), "max_level": int(max_level),
               "refer_level": int(refer_level),
               "refer_scale": float(refer_scale)},
    )
    return outs, restore


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_ids=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_ids is not None:
        ins["BatchId"] = [batch_ids]
    return emit_op(
        "prroi_pool", ins,
        {"spatial_scale": float(spatial_scale),
         "pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width)},
    )


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, batch_ids=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_ids is not None:
        ins["BatchId"] = [batch_ids]
    return emit_op(
        "psroi_pool", ins,
        {"output_channels": int(output_channels),
         "spatial_scale": float(spatial_scale),
         "pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width)},
    )


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              batch_ids=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_ids is not None:
        ins["BatchId"] = [batch_ids]
    return emit_op(
        "roi_perspective_transform", ins,
        {"transformed_height": int(transformed_height),
         "transformed_width": int(transformed_width),
         "spatial_scale": float(spatial_scale)},
    )


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=None, deformable_groups=None,
                    im2col_step=None, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """Deformable conv v1 (modulated=False) / v2 (reference
    deformable_conv_op.cc)."""
    from ..initializer import NormalInitializer
    from ..layer_helper import LayerHelper

    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    c = input.shape[1]
    g = int(groups or 1)
    fs = [filter_size, filter_size] if isinstance(filter_size, int) \
        else list(filter_size)
    std = (2.0 / (fs[0] * fs[1] * (c // g))) ** 0.5  # He init over fan-in
    # [Co, C/g, kh, kw] — the reference conv filter layout under groups
    w = helper.create_parameter(
        helper.param_attr, shape=[num_filters, c // g, fs[0], fs[1]],
        dtype=dtype, default_initializer=NormalInitializer(0.0, std))
    ins = {"Input": [input], "Offset": [offset], "Filter": [w]}
    if modulated and mask is not None:
        ins["Mask"] = [mask]
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="deformable_conv", inputs=ins, outputs={"Output": [out]},
        attrs={"strides": [stride, stride] if isinstance(stride, int) else stride,
               "paddings": [padding, padding] if isinstance(padding, int) else padding,
               "dilations": [dilation, dilation] if isinstance(dilation, int) else dilation,
               "groups": groups or 1,
               "deformable_groups": deformable_groups or 1},
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=True,
                           batch_ids=None, name=None):
    oc = input.shape[1] // (pooled_height * pooled_width) \
        if position_sensitive else input.shape[1]
    ins = {"Input": [input], "ROIs": [rois]}
    if trans is not None and not no_trans:
        ins["Trans"] = [trans]
    if batch_ids is not None:
        ins["BatchId"] = [batch_ids]
    return emit_op(
        "deformable_psroi_pooling", ins,
        {"output_channels": int(oc), "spatial_scale": float(spatial_scale),
         "pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width),
         "trans_std": float(trans_std), "no_trans": bool(no_trans)},
        out_slots=("Output",),
    )


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, name=None):
    return emit_op(
        "yolov3_loss",
        {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]},
        {"anchors": [float(a) for a in anchors],
         "anchor_mask": [int(m) for m in anchor_mask],
         "class_num": int(class_num),
         "ignore_thresh": float(ignore_thresh),
         "downsample_ratio": int(downsample_ratio)},
        out_slots=("Loss",),
    )


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """Fast R-CNN training sampler (reference
    generate_proposal_labels_op.cc — a CPU-only op there too): runs
    host-side via py_func with FIXED batch_size_per_im outputs per image.
    rpn_rois [N, R, 4]; gt_* [N, G, ...] zero/-1 padded.
    Returns (rois [N, B, 4], labels [N, B], bbox_targets [N, B, 4*C'],
    inside_w, outside_w) with C' = 1 if is_cls_agnostic else class_nums."""
    import numpy as np

    from .control_flow import py_func
    from ..layer_helper import LayerHelper

    helper = LayerHelper("generate_proposal_labels")
    n, r = rpn_rois.shape[0], rpn_rois.shape[1]
    b = int(batch_size_per_im)
    creg = 1 if is_cls_agnostic else int(class_nums)

    def _sample(rois_np, gtc, gtb):
        rng = np.random.RandomState(0 if not use_random else None)
        out_rois = np.zeros((n, b, 4), np.float32)
        out_lbl = np.zeros((n, b), np.int32)
        out_tgt = np.zeros((n, b, 4 * creg), np.float32)
        out_in = np.zeros((n, b, 4 * creg), np.float32)
        for i in range(n):
            valid_gt = gtc[i] >= 0
            boxes = np.concatenate([rois_np[i], gtb[i][valid_gt]], axis=0)
            gtbi = gtb[i][valid_gt]
            if len(gtbi) == 0:
                sel = rng.choice(len(boxes), b, replace=len(boxes) < b)
                out_rois[i] = boxes[sel]
                continue
            # IoU
            x1 = np.maximum(boxes[:, None, 0], gtbi[None, :, 0])
            y1 = np.maximum(boxes[:, None, 1], gtbi[None, :, 1])
            x2 = np.minimum(boxes[:, None, 2], gtbi[None, :, 2])
            y2 = np.minimum(boxes[:, None, 3], gtbi[None, :, 3])
            inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
            area_b = ((boxes[:, 2] - boxes[:, 0])
                      * (boxes[:, 3] - boxes[:, 1]))[:, None]
            area_g = ((gtbi[:, 2] - gtbi[:, 0])
                      * (gtbi[:, 3] - gtbi[:, 1]))[None, :]
            iou = inter / np.maximum(area_b + area_g - inter, 1e-10)
            best = iou.max(axis=1)
            best_gt = iou.argmax(axis=1)
            fg = np.where(best >= fg_thresh)[0]
            bg = np.where((best < bg_thresh_hi) & (best >= bg_thresh_lo))[0]
            n_fg = min(int(b * fg_fraction), len(fg))
            n_bg = min(b - n_fg, len(bg))
            fg_sel = rng.choice(fg, n_fg, replace=False) if n_fg else fg[:0]
            bg_sel = rng.choice(bg, n_bg, replace=False) if n_bg else bg[:0]
            sel = np.concatenate([fg_sel, bg_sel])
            if len(sel) < b:  # pad by repeating backgrounds/foregrounds
                extra = rng.choice(len(boxes), b - len(sel), replace=True)
                sel = np.concatenate([sel, extra])
            out_rois[i] = boxes[sel]
            lbl = np.zeros(len(sel), np.int32)
            lbl[: n_fg] = gtc[i][valid_gt][best_gt[fg_sel]] if n_fg else lbl[:0]
            out_lbl[i] = lbl
            # bbox targets for fg
            for j in range(n_fg):
                bidx = sel[j]
                g = gtbi[best_gt[bidx]]
                bx = boxes[bidx]
                bw = max(bx[2] - bx[0], 1e-6)
                bh = max(bx[3] - bx[1], 1e-6)
                gw = max(g[2] - g[0], 1e-6)
                gh = max(g[3] - g[1], 1e-6)
                d = np.asarray([
                    ((g[0] + g[2]) / 2 - (bx[0] + bx[2]) / 2) / bw / bbox_reg_weights[0],
                    ((g[1] + g[3]) / 2 - (bx[1] + bx[3]) / 2) / bh / bbox_reg_weights[1],
                    np.log(gw / bw) / bbox_reg_weights[2],
                    np.log(gh / bh) / bbox_reg_weights[3]], np.float32)
                cls = 0 if is_cls_agnostic else int(lbl[j])
                out_tgt[i, j, 4 * cls: 4 * cls + 4] = d
                out_in[i, j, 4 * cls: 4 * cls + 4] = 1.0
        return out_rois, out_lbl, out_tgt, out_in, out_in.copy()

    outs = []
    for dt, shape in [("float32", (n, b, 4)), ("int32", (n, b)),
                      ("float32", (n, b, 4 * creg)),
                      ("float32", (n, b, 4 * creg)),
                      ("float32", (n, b, 4 * creg))]:
        v = helper.create_variable_for_type_inference(dt)
        v.shape = shape
        outs.append(v)
    py_func(_sample, x=[rpn_rois, gt_classes, gt_boxes], out=outs)
    return tuple(outs)


def _poly_fill(xs, ys, m):
    """Even-odd polygon fill at pixel centers (x+.5, y+.5) on an m x m
    grid — the numpy equivalent of the reference's COCO
    upsample-walk-RLE rasterizer (mask_util.cc Poly2Mask)."""
    inside = np.zeros((m, m), bool)
    cy = (np.arange(m) + 0.5)[:, None]
    cx = (np.arange(m) + 0.5)[None, :]
    k = len(xs)
    for e in range(k):
        x1, y1 = xs[e], ys[e]
        x2, y2 = xs[(e + 1) % k], ys[(e + 1) % k]
        if y1 == y2:
            continue
        crosses = (y1 <= cy) != (y2 <= cy)
        xc = x1 + (cy - y1) * (x2 - x1) / (y2 - y1)
        inside ^= crosses & (cx < xc)
    return inside


def _polys_to_mask_wrt_box(polys, box, m):
    """Union-rasterize `polys` (list of [K,2] arrays, image coords) into
    the m x m grid of `box` (reference mask_util.cc Polys2MaskWrtBox)."""
    w = max(box[2] - box[0], 1.0)
    h = max(box[3] - box[1], 1.0)
    mask = np.zeros((m, m), bool)
    for p in polys:
        xs = (p[:, 0] - box[0]) * m / w
        ys = (p[:, 1] - box[1]) * m / h
        mask |= _poly_fill(xs, ys, m)
    return mask.astype(np.uint8)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         segm_lengths=None):
    """Mask R-CNN mask targets (reference generate_mask_labels_op.cc,
    SampleMaskForOneImage): every fg roi is matched to the gt whose
    polygon bounding box overlaps it most, the gt's polygons are
    rasterized into the roi's resolution x resolution grid, and the
    binary mask lands in the roi label's class slot (-1 elsewhere: the
    ignore value ExpandMaskTarget writes).

    Padded-batch convention (this framework's replacement for the
    reference's 3-level LoD): gt_segms [N, G, P, V, 2] float32 holds up
    to P polygons of up to V vertices per gt box, with `segm_lengths`
    [N, G, P] int32 giving each polygon's true vertex count (0 = no
    polygon). gt_classes / is_crowd [N, G] (class <= 0 = padding), rois
    [N, R, 4], labels_int32 [N, R].

    Returns (mask_rois [N, R, 4], roi_has_mask_int32 [N, R],
    mask_int32 [N, R, num_classes * resolution**2], mask_nums [N]):
    rows beyond mask_nums[i] are -1/0 padding.
    """
    if segm_lengths is None:
        raise ValueError(
            "generate_mask_labels: pass segm_lengths [N, G, P] int32 — "
            "the padded-batch replacement for the reference's gt_segms "
            "LoD levels"
        )
    from .control_flow import py_func

    helper = LayerHelper("generate_mask_labels")
    n, r = rois.shape[0], rois.shape[1]
    m = int(resolution)
    mask_dim = int(num_classes) * m * m

    def _sample(iminfo, gtc, crowd, segms, seglen, rois_np, labels):
        out_rois = np.zeros((n, r, 4), np.float32)
        out_has = np.full((n, r), -1, np.int32)
        out_mask = np.full((n, r, mask_dim), -1, np.int32)
        out_num = np.zeros((n,), np.int32)
        for i in range(n):
            im_scale = float(iminfo[i, 2])
            # gts carrying a mask: fg class, not crowd, >=1 real polygon
            polys_per_gt = []
            for gi in range(gtc.shape[1]):
                if gtc[i, gi] <= 0 or crowd[i, gi] != 0:
                    continue
                polys = [
                    segms[i, gi, pi, : seglen[i, gi, pi]]
                    for pi in range(seglen.shape[2])
                    if seglen[i, gi, pi] >= 3
                ]
                if polys:
                    polys_per_gt.append(polys)
            fg = np.where(labels[i] > 0)[0]
            if len(fg) == 0 or not polys_per_gt:
                # reference fallback: one bg roi with an all -1 mask
                bg = np.where(labels[i] == 0)[0]
                bg0 = int(bg[0]) if len(bg) else 0
                out_num[i] = 1
                out_has[i, 0] = bg0
                out_rois[i, 0] = rois_np[i, bg0]
                continue
            # bbox enclosing each gt's polygons (Poly2Boxes)
            gt_boxes = np.array([
                [
                    min(p[:, 0].min() for p in ps),
                    min(p[:, 1].min() for p in ps),
                    max(p[:, 0].max() for p in ps),
                    max(p[:, 1].max() for p in ps),
                ]
                for ps in polys_per_gt
            ], np.float32)
            fg_rois = rois_np[i, fg] / max(im_scale, 1e-12)
            x1 = np.maximum(fg_rois[:, None, 0], gt_boxes[None, :, 0])
            y1 = np.maximum(fg_rois[:, None, 1], gt_boxes[None, :, 1])
            x2 = np.minimum(fg_rois[:, None, 2], gt_boxes[None, :, 2])
            y2 = np.minimum(fg_rois[:, None, 3], gt_boxes[None, :, 3])
            inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
            a_r = ((fg_rois[:, 2] - fg_rois[:, 0])
                   * (fg_rois[:, 3] - fg_rois[:, 1]))[:, None]
            a_g = ((gt_boxes[:, 2] - gt_boxes[:, 0])
                   * (gt_boxes[:, 3] - gt_boxes[:, 1]))[None, :]
            iou = inter / np.maximum(a_r + a_g - inter, 1e-10)
            best_gt = iou.argmax(axis=1)
            out_num[i] = len(fg)
            out_has[i, : len(fg)] = fg
            out_rois[i, : len(fg)] = fg_rois * im_scale
            for j, (roi_idx, gt_j) in enumerate(zip(fg, best_gt)):
                msk = _polys_to_mask_wrt_box(
                    polys_per_gt[gt_j], fg_rois[j], m
                )
                cls = int(labels[i, roi_idx])
                if 0 < cls < num_classes:
                    out_mask[i, j, cls * m * m:(cls + 1) * m * m] = (
                        msk.reshape(-1)
                    )
        return out_rois, out_has, out_mask, out_num

    outs = []
    for dt, shape in [("float32", (n, r, 4)), ("int32", (n, r)),
                      ("int32", (n, r, mask_dim)), ("int32", (n,))]:
        v = helper.create_variable_for_type_inference(dt)
        v.shape = shape
        outs.append(v)
    py_func(_sample, x=[im_info, gt_classes, is_crowd, gt_segms,
                        segm_lengths, rois, labels_int32], out=outs)
    return tuple(outs)
