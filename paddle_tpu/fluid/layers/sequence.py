"""Sequence & RNN layers (`fluid.layers.sequence_* / dynamic_lstm / ...`).

Parity surface: reference python/paddle/fluid/layers/sequence_lod.py +
nn.py (dynamic_lstm:466, dynamic_gru:855, sequence_conv, sequence_pool,
sequence_softmax, sequence_expand, linear_chain_crf, crf_decoding, warpctc,
edit_distance, beam_search).

Padded+mask convention (ops/sequence_ops.py): sequences are dense
[B, T, ...] tensors; pass `length` ([B] int32 variable) wherever the
reference relied on LoD to mark ragged rows.
"""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper

__all__ = [
    "distributed_embedding",
    "sequence_mask", "sequence_pool", "sequence_first_step",
    "sequence_last_step", "sequence_softmax", "sequence_reverse",
    "sequence_expand", "sequence_expand_as", "sequence_conv",
    "sequence_pad", "sequence_unpad", "dynamic_lstm", "dynamic_gru",
    "linear_chain_crf", "crf_decoding", "warpctc", "edit_distance",
    "beam_search", "sequence_concat", "sequence_enumerate",
    "sequence_slice", "sequence_scatter", "sequence_reshape",
    "gather_tree", "lod_reset", "lod_append", "im2sequence_alias", "row_conv",
    "reorder_lod_tensor_by_rank",
]


def _seq_inputs(x, length):
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    return ins


def sequence_mask(x, maxlen, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_mask", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"maxlen": int(maxlen), "out_dtype": np.dtype(dtype)},
    )
    return out


def sequence_pool(input, pool_type, length=None, name=None):
    helper = LayerHelper("sequence_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    outs = {"Out": [out]}
    if pool_type.upper() == "MAX":
        idx = helper.create_variable_for_type_inference("int32")
        outs["MaxIndex"] = [idx]
    helper.append_op(
        type="sequence_pool", inputs=_seq_inputs(input, length),
        outputs=outs, attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_first_step(input, length=None):
    return sequence_pool(input, "FIRST", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "LAST", length)


def sequence_softmax(input, length=None, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_softmax", inputs=_seq_inputs(input, length),
        outputs={"Out": [out]},
    )
    return out


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_reverse", inputs=_seq_inputs(x, length),
        outputs={"Y": [out]},
    )
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]}, attrs={"ref_level": ref_level},
    )
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand_as", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def sequence_conv(
    input, num_filters, filter_size=3, filter_stride=1, padding=True,
    padding_start=None, length=None, param_attr=None, bias_attr=None,
    act=None, name=None,
):
    if filter_stride != 1:
        raise NotImplementedError(
            "sequence_conv: filter_stride must be 1 (the reference enforces "
            "the same)"
        )
    helper = LayerHelper(
        "sequence_conv", param_attr=param_attr, bias_attr=bias_attr,
        act=act, name=name,
    )
    dtype = input.dtype
    d = input.shape[-1]
    w = helper.create_parameter(
        helper.param_attr, shape=[filter_size * d, num_filters], dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    if padding_start is None:
        padding_start = -(filter_size - 1) // 2
    ins = _seq_inputs(input, length)
    ins["Filter"] = [w]
    helper.append_op(
        type="sequence_conv", inputs=ins, outputs={"Out": [out]},
        attrs={"contextLength": filter_size, "contextStart": padding_start,
               "contextStride": filter_stride},
    )
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_pad(x, pad_value=None, maxlen=None, length=None, name=None):
    if pad_value is not None:
        raise NotImplementedError(
            "sequence_pad: inputs are already dense/padded in this framework; "
            "a custom pad_value is not representable (pads stay as provided)"
        )
    if maxlen is not None and maxlen != x.shape[1]:
        raise NotImplementedError(
            f"sequence_pad: maxlen={maxlen} != static time width {x.shape[1]}"
        )
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    lvar = helper.create_variable_for_type_inference("int32")
    ins = _seq_inputs(x, length)
    helper.append_op(
        type="sequence_pad", inputs=ins,
        outputs={"Out": [out], "Length": [lvar]}, attrs={},
    )
    return out, lvar


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_unpad", inputs=_seq_inputs(x, length),
        outputs={"Out": [out]}, attrs={},
    )
    return out


def dynamic_lstm(
    input, size, h_0=None, c_0=None, length=None, param_attr=None,
    bias_attr=None, use_peepholes=False, is_reverse=False,
    gate_activation="sigmoid", cell_activation="tanh",
    candidate_activation="tanh", dtype="float32", name=None,
):
    """reference layers/nn.py dynamic_lstm:466 — input is the pre-projected
    [B, T, 4*H] tensor (apply fc(size*4) first, as in the reference)."""
    if use_peepholes:
        raise NotImplementedError(
            "peephole connections are not supported (reference default path)"
        )
    if size % 4 != 0:
        raise ValueError(f"dynamic_lstm size must be divisible by 4, got {size}")
    helper = LayerHelper(
        "dynamic_lstm", param_attr=param_attr, bias_attr=bias_attr, name=name
    )
    h = size // 4
    w = helper.create_parameter(helper.param_attr, shape=[h, 4 * h], dtype=dtype)
    bias = helper.create_parameter(
        helper.bias_attr, shape=[1, 4 * h], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [w]}
    if bias is not None:  # bias_attr=False disables the bias
        ins["Bias"] = [bias]
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        type="lstm", inputs=ins,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden, cell


def dynamic_gru(
    input, size, h_0=None, length=None, param_attr=None, bias_attr=None,
    is_reverse=False, gate_activation="sigmoid", candidate_activation="tanh",
    origin_mode=False, dtype="float32", name=None,
):
    """reference layers/nn.py dynamic_gru:855 — input is the pre-projected
    [B, T, 3*H] tensor."""
    helper = LayerHelper(
        "dynamic_gru", param_attr=param_attr, bias_attr=bias_attr, name=name
    )
    h = size
    w = helper.create_parameter(helper.param_attr, shape=[h, 3 * h], dtype=dtype)
    bias = helper.create_parameter(
        helper.bias_attr, shape=[1, 3 * h], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [w]}
    if bias is not None:  # bias_attr=False disables the bias
        ins["Bias"] = [bias]
    if h_0 is not None:
        ins["H0"] = [h_0]
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        type="gru", inputs=ins, outputs={"Hidden": [hidden]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
            "origin_mode": origin_mode,
        },
    )
    return hidden


def linear_chain_crf(input, label, param_attr=None, length=None, name=None):
    """reference layers/nn.py linear_chain_crf — returns the per-sequence
    negative log-likelihood [B,1] (minimize its mean)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr, name=name)
    d = input.shape[-1]
    trans = helper.create_parameter(
        helper.param_attr, shape=[d + 2, d], dtype=input.dtype
    )
    ll = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Emission": [input], "Transition": [trans], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        type="linear_chain_crf", inputs=ins,
        outputs={"LogLikelihood": [ll]}, attrs={},
    )
    return ll


def crf_decoding(input, param_attr, length=None, name=None):
    from .. import framework

    helper = LayerHelper("crf_decoding", name=name)
    pname = param_attr if isinstance(param_attr, str) else param_attr.name
    transition = framework.default_main_program().global_block()._find_var_recursive(pname)
    if transition is None:
        raise ValueError(f"crf_decoding: transition parameter {pname!r} not found")
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        type="crf_decoding", inputs=ins, outputs={"ViterbiPath": [path]},
        attrs={},
    )
    return path


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None, name=None):
    helper = LayerHelper("warpctc", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op(
        type="warpctc", inputs=ins, outputs={"Loss": [loss]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    helper = LayerHelper("edit_distance", name=name)
    out = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int64")
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    helper.append_op(
        type="edit_distance", inputs=ins,
        outputs={"Out": [out], "SequenceNum": [num]},
        attrs={"normalized": normalized},
    )
    return out, num


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, name=None):
    """One step of beam search over a flattened [B*W] beam batch
    (reference layers/nn.py beam_search / beam_search_op.cc). Returns
    (selected_ids [B*W,1], selected_scores [B*W,1], parent_idx [B*W])."""
    helper = LayerHelper("beam_search", name=name)
    ids = helper.create_variable_for_type_inference(pre_ids.dtype)
    sc = helper.create_variable_for_type_inference("float32")
    parent = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores], "scores": [scores]},
        outputs={
            "selected_ids": [ids], "selected_scores": [sc], "parent_idx": [parent],
        },
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return ids, sc, parent


def distributed_embedding(input, table_name, name=None):
    """Look up rows of a host-resident PS table (distributed.ps) —
    reference layers distributed_lookup_table / fleet PS embedding.
    The table must have been created with distributed.ps.create_table;
    its optimizer runs server-side, so the program only carries a (1,)
    zero anchor Parameter that routes autodiff through the op."""
    from ...distributed import ps
    from ..initializer import ConstantInitializer
    from ..param_attr import ParamAttr

    table = ps.get_table(table_name)
    helper = LayerHelper("distributed_embedding", name=name)
    anchor = helper.create_parameter(
        ParamAttr(name=f"{table_name}_anchor",
                  initializer=ConstantInitializer(0.0)),
        shape=[1], dtype="float32",
    )
    out = helper.create_variable_for_type_inference(str(np.dtype(table.dtype)))
    helper.append_op(
        type="distributed_lookup_table",
        inputs={"Ids": [input], "W": [anchor]},
        outputs={"Outputs": [out]},
        attrs={"table_names": [table_name]},
    )
    return out


def sequence_concat(input, lengths=None, name=None):
    """Ragged time-axis concat on padded rows (reference
    sequence_concat_op.cc). `lengths`: optional list of [B] per-input
    valid lengths. With lengths, returns (packed [B, sum(Ti), ...],
    out_lengths [B]) — downstream sequence_* layers need the summed
    lengths explicitly under the padded+mask convention; without lengths
    (fully valid rows) returns just the tensor, like the reference."""
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    ins = {"X": list(input)}
    if lengths is not None:
        from . import tensor as _tensor

        ins["Length"] = [_tensor.concat([l for l in lengths], axis=0)]
    helper.append_op(type="sequence_concat", inputs=ins,
                     outputs={"Out": [out], "Length": [out_len]})
    return (out, out_len) if lengths is not None else out


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = _seq_inputs(input, length)
    helper.append_op(type="sequence_enumerate", inputs=ins,
                     outputs={"Out": [out]},
                     attrs={"win_size": int(win_size),
                            "pad_value": int(pad_value)})
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-row subsequence: row b keeps input[b, offset_b:offset_b+length_b]
    left-aligned (padded+mask analog of sequence_slice_op.cc)."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out], "OutLength": [out_len]},
    )
    return out


def sequence_scatter(input, index, updates, length=None, name=None):
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_scatter", inputs=ins,
                     outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim, name=None):
    helper = LayerHelper("sequence_reshape", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"new_dim": int(new_dim)})
    return out


def gather_tree(ids, parents):
    """Beam-search backtrace (reference gather_tree_op.cc):
    ids/parents [T, B, W] -> full id paths [T, B, W]."""
    helper = LayerHelper("gather_tree")
    out = helper.create_variable_for_type_inference(ids.dtype)
    helper.append_op(type="gather_tree",
                     inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]})
    return out


def lod_reset(x, y=None, target_lod=None):
    """LoD is explicit on TPU: sequence lengths travel as a separate
    `length` argument to each sequence_* layer rather than as tensor
    metadata (SURVEY.md §7 LoD answer), so resetting LoD is a no-op on
    the data — pass the new lengths to the next sequence op instead."""
    return x


def lod_append(x, level):
    """See lod_reset: lengths are explicit arguments on TPU."""
    return x


def im2sequence_alias(*a, **k):  # pragma: no cover — vision.py owns it
    from .vision import im2sequence

    return im2sequence(*a, **k)


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder batch rows by a rank index (dense analog of the
    reference's rank-table reorder): rank_table is an int [B] index."""
    from . import nn as _nn

    return _nn.gather(x, rank_table)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference layers/nn.py row_conv over
    row_conv_op.cc): input [B, T, D], filter [future_context+1, D]."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = input.shape[-1]
    f = helper.create_parameter(
        helper.param_attr, shape=[int(future_context_size) + 1, d],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [f]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)
