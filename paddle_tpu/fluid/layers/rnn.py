"""RNN cells, the functional `rnn()` runner, and seq2seq decoding.

Parity surface: reference python/paddle/fluid/layers/rnn.py — RNNCell,
GRUCell, LSTMCell, rnn, Decoder, BasicDecoder (+ TrainingHelper /
GreedyEmbeddingHelper / SampleEmbeddingHelper), BeamSearchDecoder,
dynamic_decode, beam_search_decode; plus nn.py lstm_unit / gru_unit /
lstm / dynamic_lstmp.

TPU-native design: recurrences run through the StaticRNN `recurrent` op
(one lax.scan body, SURVEY.md §7 SSA-ification of per-step scopes);
decoding unrolls a STATIC max_step_num with a `finished` mask instead of
the reference's dynamic while-loop + growing LoD arrays — fixed shapes,
one compiled program, masked tails.
"""
from __future__ import annotations

import numpy as np

from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from . import nn as _nn
from . import ops as _ops
from . import tensor as _tensor
from .control_flow import StaticRNN

__all__ = [
    "RNNCell", "GRUCell", "LSTMCell", "rnn", "birnn", "birnn_unsupported",
    "Decoder", "BasicDecoder", "DecodeHelper", "TrainingHelper",
    "GreedyEmbeddingHelper", "SampleEmbeddingHelper", "BeamSearchDecoder",
    "dynamic_decode", "beam_search_decode", "lstm_unit", "gru_unit",
    "lstm", "dynamic_lstmp",
]


class RNNCell:
    """Base cell (reference rnn.py RNNCell): call(inputs, states) ->
    (outputs, new_states); get_initial_states builds zeros."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)

    @property
    def state_shape(self):
        raise NotImplementedError

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        shapes = shape if shape is not None else self.state_shape
        single = not isinstance(shapes[0], (list, tuple))
        if single:
            shapes = [shapes]
        b = batch_ref.shape[batch_dim_idx]
        inits = [
            _tensor.fill_constant([b] + list(s), dtype, init_value)
            for s in shapes
        ]
        return inits[0] if single else inits


class LSTMCell(RNNCell):
    """Standard LSTM cell (reference rnn.py LSTMCell): state = (h, c)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, forget_bias=1.0,
                 dtype="float32", name="LSTMCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._forget_bias = float(forget_bias)
        self._dtype = dtype
        self._name = name

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]

    def call(self, inputs, states):
        h, c = states
        concat = _tensor.concat([inputs, h], axis=1)
        gates = _nn.fc(
            concat, 4 * self.hidden_size,
            param_attr=self._param_attr or ParamAttr(name=f"{self._name}.w_0"),
            bias_attr=self._bias_attr or ParamAttr(name=f"{self._name}.b_0"),
        )
        i, f, ct, o = _nn.split(gates, 4, dim=1)
        f = _nn.scale(f, bias=self._forget_bias)
        new_c = _nn.elementwise_add(
            _nn.elementwise_mul(c, _ops.sigmoid(f)),
            _nn.elementwise_mul(_ops.sigmoid(i), _ops.tanh(ct)),
        )
        new_h = _nn.elementwise_mul(_ops.tanh(new_c), _ops.sigmoid(o))
        return new_h, [new_h, new_c]


class GRUCell(RNNCell):
    """GRU cell (reference rnn.py GRUCell): state = h."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype="float32",
                 name="GRUCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._name = name

    @property
    def state_shape(self):
        return [self.hidden_size]

    def _sub_attr(self, attr, sub, kind):
        """User attrs apply to BOTH internal fcs (gate + candidate): keep
        the user's initializer/settings but suffix the name so the two
        weights stay distinct."""
        if attr is None:
            return ParamAttr(name=f"{self._name}.{sub}.{kind}")
        a = ParamAttr._to_attr(attr)
        base = a.name or self._name
        return ParamAttr(name=f"{base}.{sub}.{kind}", initializer=a.initializer)

    def call(self, inputs, states):
        h = states[0] if isinstance(states, (list, tuple)) else states
        concat = _tensor.concat([inputs, h], axis=1)
        gates = _nn.fc(
            concat, 2 * self.hidden_size,
            param_attr=self._sub_attr(self._param_attr, "gate", "w_0"),
            bias_attr=self._sub_attr(self._bias_attr, "gate", "b_0"),
            act="sigmoid",
        )
        r, u = _nn.split(gates, 2, dim=1)
        cand = _nn.fc(
            _tensor.concat([inputs, _nn.elementwise_mul(r, h)], axis=1),
            self.hidden_size,
            param_attr=self._sub_attr(self._param_attr, "cand", "w_0"),
            bias_attr=self._sub_attr(self._bias_attr, "cand", "b_0"),
            act="tanh",
        )
        new_h = _nn.elementwise_add(
            _nn.elementwise_mul(u, h),
            _nn.elementwise_mul(_nn.scale(u, scale=-1.0, bias=1.0), cand),
        )
        return new_h, [new_h]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run a cell over the time axis (reference rnn.py rnn): one scanned
    step block. inputs [B, T, D] (or [T, B, D] time_major)."""
    if time_major:
        inputs = _nn.transpose(inputs, [1, 0, 2])
    if initial_states is None:
        initial_states = cell.get_initial_states(batch_ref=inputs)
    states = initial_states if isinstance(initial_states, (list, tuple)) \
        else [initial_states]

    mask3 = None
    if sequence_length is not None:
        from . import sequence as _seq

        mask = _seq.sequence_mask(sequence_length, maxlen=inputs.shape[1],
                                  dtype="float32")
        mask3 = _nn.reshape(mask, [inputs.shape[0], inputs.shape[1], 1])

    srnn = StaticRNN(is_reverse=is_reverse)
    with srnn.step():
        x_t = srnn.step_input(inputs)
        m_t = srnn.step_input(mask3) if mask3 is not None else None
        mems = [srnn.memory(init=s) for s in states]
        out, new_states = cell.call(x_t, mems)
        for mem, ns in zip(mems, new_states):
            if m_t is not None:
                ns = _nn.elementwise_add(
                    _nn.elementwise_mul(ns, m_t),
                    _nn.elementwise_mul(mem, _nn.scale(m_t, -1.0, bias=1.0)),
                )
            srnn.update_memory(mem, ns)
        if m_t is not None:
            out = _nn.elementwise_mul(out, m_t)
        srnn.output(out)
    outputs = srnn()
    if time_major:
        outputs = _nn.transpose(outputs, [1, 0, 2])
    # FINAL states (reference rnn.py contract) — the recurrent op's
    # FinalStates outputs, already length-masked by the update freeze
    return outputs, srnn.final_states


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    """Bidirectional rnn (reference rnn.py birnn): run cell_fw forward
    and cell_bw reverse-scanned over the same inputs, concat outputs on
    the feature dim. Returns (outputs, (fw_final, bw_final))."""
    states_fw = states_bw = None
    if initial_states is not None:
        states_fw, states_bw = initial_states
    out_fw, fin_fw = rnn(cell_fw, inputs, states_fw,
                         sequence_length=sequence_length,
                         time_major=time_major, **kwargs)
    out_bw, fin_bw = rnn(cell_bw, inputs, states_bw,
                         sequence_length=sequence_length,
                         time_major=time_major, is_reverse=True, **kwargs)
    outputs = _tensor.concat([out_fw, out_bw], axis=2)
    return outputs, (fin_fw, fin_bw)


birnn_unsupported = birnn  # legacy alias (pre-round-4 name)


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------


class DecodeHelper:
    """Sampling strategy for BasicDecoder (reference rnn.py helpers)."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: read the next input from the ground-truth slice."""

    def __init__(self, inputs, sequence_length=None, time_major=False):
        self._inputs = _nn.transpose(inputs, [1, 0, 2]) if time_major else inputs
        self._length = sequence_length

    def initialize(self):
        first = _nn.slice(self._inputs, axes=[1], starts=[0], ends=[1])
        init_inputs = _nn.reshape(
            first, [self._inputs.shape[0]] + list(self._inputs.shape[2:]))
        b = self._inputs.shape[0]
        finished = _tensor.fill_constant([b], "float32", 0.0)
        return init_inputs, finished

    def sample(self, time, outputs, states):
        return _tensor.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        t = self._inputs.shape[1]
        nxt = min(time + 1, t - 1)
        sl = _nn.slice(self._inputs, axes=[1], starts=[nxt], ends=[nxt + 1])
        nxt_in = _nn.reshape(
            sl, [self._inputs.shape[0]] + list(self._inputs.shape[2:]))
        b = self._inputs.shape[0]
        if self._length is not None:
            done = _tensor.cast(
                _tensor.less_than(
                    _tensor.cast(self._length, "int64"),
                    _tensor.fill_constant([b], "int64", time + 2)),
                "float32")
        else:
            done = _tensor.fill_constant(
                [b], "float32", 1.0 if time + 1 >= t else 0.0)
        return nxt_in, states, done


class GreedyEmbeddingHelper(DecodeHelper):
    """Feed back the argmax token's embedding (reference rnn.py)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self._embed = embedding_fn
        self._start = start_tokens  # [B] int
        self._end = int(end_token)

    def initialize(self):
        b = self._start.shape[0]
        return self._embed(self._start), _tensor.fill_constant([b], "float32", 0.0)

    def sample(self, time, outputs, states):
        return _tensor.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        done = _tensor.cast(
            _tensor.equal(
                sample_ids,
                _tensor.fill_constant(list(sample_ids.shape),
                                      sample_ids.dtype, self._end)),
            "float32")
        return self._embed(sample_ids), states, done


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Feed back a SAMPLED token's embedding (reference rnn.py)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self._temp = softmax_temperature
        self._seed = seed or 0

    def sample(self, time, outputs, states):
        from .misc import sampling_id

        logits = outputs if self._temp is None else _nn.scale(
            outputs, scale=1.0 / self._temp)
        probs = _nn.softmax(logits)
        return _tensor.cast(
            sampling_id(probs, seed=self._seed + time), "int64")


class Decoder:
    """Base decoder (reference rnn.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BasicDecoder(Decoder):
    """cell + helper + optional output layer (reference rnn.py
    BasicDecoder). step -> ((cell_out, sample_ids), states, next_inputs,
    finished)."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        inputs, finished = self.helper.initialize()
        return inputs, initial_cell_states, finished

    def step(self, time, inputs, states):
        cell_outputs, cell_states = self.cell.call(inputs, states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        next_inputs, next_states, finished = self.helper.next_inputs(
            time, cell_outputs, cell_states, sample_ids)
        return (cell_outputs, sample_ids), next_states, next_inputs, finished


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   **kwargs):
    """Run a decoder to completion (reference rnn.py dynamic_decode).

    TPU-native: the reference loops a While op until all rows finish,
    appending to LoD arrays; here max_step_num is a STATIC bound — all
    steps run, a `finished` mask freezes completed rows, outputs keep a
    fixed [B, Tmax, ...] shape. max_step_num is therefore required."""
    if max_step_num is None:
        raise ValueError(
            "dynamic_decode on TPU needs a static max_step_num (fixed-shape "
            "decode loop; finished rows are masked, not skipped)")
    inputs, states, finished = decoder.initialize(inits)
    # finished rows pad their sample ids with the decoder's end token
    # (reference semantics) rather than 0 — id 0 is a real vocab token in
    # this repo's datasets (wmt16 <s> == 0), so zero-padding would
    # misparse for consumers that ignore the returned lengths.
    pad_id = 0
    helper_end = getattr(getattr(decoder, "helper", None), "_end", None)
    if helper_end is not None:
        pad_id = int(helper_end)
    elif getattr(decoder, "end", None) is not None:
        pad_id = int(decoder.end)
    step_outputs, step_ids = [], []
    length_acc = None
    # a decoder that tracks its own finished rows (BeamSearchDecoder:
    # finished beams only ever extend with (end_token, parent=self) at
    # unchanged score) already emits well-formed outputs past the end
    # token — masking them to zero here would corrupt the (token,
    # parent) pairs gather_tree backtraces through
    own_finished = bool(getattr(decoder, "tracks_own_finished", False))
    for t in range(int(max_step_num)):
        (out, ids), next_states, next_inputs, step_finished = decoder.step(
            t, inputs, states)
        alive = _nn.scale(finished, scale=-1.0, bias=1.0)  # [B]
        if not own_finished:
            # freeze finished rows: keep emitting, mask below
            am = _nn.reshape(alive, [out.shape[0], 1])
            out = _nn.elementwise_mul(out, am)
            alive_ids = (
                _nn.reshape(alive,
                            [ids.shape[0]] + [1] * (len(ids.shape) - 1))
                if len(ids.shape) > 1 else alive)
            ids = _tensor.cast(
                _nn.elementwise_add(
                    _nn.elementwise_mul(
                        _tensor.cast(ids, "float32"), alive_ids),
                    _nn.scale(alive_ids, scale=-float(pad_id),
                              bias=float(pad_id)),
                ),
                "int64")
        step_outputs.append(out)
        step_ids.append(ids)
        inputs, states = next_inputs, next_states
        # per-row decoded length: count steps where the row was alive
        length_acc = alive if length_acc is None else _nn.elementwise_add(
            length_acc, alive)
        finished = _nn.elementwise_max(finished, step_finished)
    outputs = _nn.stack(step_outputs, axis=1)  # [B, T, ...]
    ids = _nn.stack(step_ids, axis=1)
    if output_time_major:
        outputs = _nn.transpose(outputs, [1, 0, 2])
        ids = _nn.transpose(ids, [1, 0] + list(range(2, len(ids.shape))))
    lengths = _tensor.cast(length_acc, "int64")  # [B(,W)] rows decoded
    return (outputs, ids), states, lengths


class BeamSearchDecoder(Decoder):
    """Beam-search decoding (reference rnn.py BeamSearchDecoder), built on
    the registered `beam_search` op per step + gather_tree backtrace.
    Kept deliberately minimal: use `beam_search_step` + layers.gather_tree
    for custom loops; dynamic_decode(BeamSearchDecoder(...)) covers the
    standard embed -> cell -> project -> top-k flow."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn, output_fn, vocab_size):
        self.cell = cell
        self.start = start_token
        self.end = int(end_token)
        self.beam = int(beam_size)
        self.embed = embedding_fn
        self.output_fn = output_fn
        self.vocab = int(vocab_size)

    @property
    def tracks_own_finished(self):
        # step() masks finished beams itself (end-token-only extension),
        # so dynamic_decode must NOT zero the (token, parent) outputs
        return True

    def initialize(self, initial_cell_states):
        b = initial_cell_states[0].shape[0]
        # tile states beam-wise: [B, ...] -> [B*W, ...]
        states = [
            _nn.reshape(
                _nn.expand(_nn.unsqueeze(s, [1]), [1, self.beam] + [1] * (len(s.shape) - 1)),
                [b * self.beam] + list(s.shape[1:]))
            for s in initial_cell_states
        ]
        start = _tensor.fill_constant([b * self.beam], "int64", self.start)
        finished = _tensor.fill_constant([b * self.beam], "float32", 0.0)
        self._batch = b
        self._log_probs = _tensor.assign(
            np.tile(np.asarray([[0.0] + [-1e9] * (self.beam - 1)], "float32"),
                    (b, 1)).reshape(-1))  # only beam 0 alive at t=0
        # finished mask threaded through step(): a finished beam's only
        # viable continuation is end_token at its UNCHANGED cumulative
        # score (reference BeamSearchDecoder._mask_probs) — without the
        # mask a finished hypothesis keeps expanding with fresh tokens
        # and the backtrace emits garbage after the first end_token
        self._finished = finished
        noend = np.full((1, self.vocab), -1e9, "float32")
        noend[0, self.end] = 0.0
        self._noend_mask = _tensor.assign(noend)
        return self.embed(start), states, finished

    def step(self, time, inputs, states):
        cell_out, cell_states = self.cell.call(inputs, states)
        logits = self.output_fn(cell_out)  # [B*W, V]
        logp = _nn.log_softmax(logits)
        cum = _nn.reshape(self._log_probs, [self._batch * self.beam, 1])
        total = _nn.elementwise_add(logp, cum)
        # finished beams: every candidate except end_token is masked to
        # -1e9 and end_token carries the beam's cumulative score
        # unchanged, so when selected the beam re-emits (end, parent=
        # self) — the gather_tree coherence contract
        fin = _nn.reshape(self._finished, [self._batch * self.beam, 1])
        alive_m = _nn.scale(fin, scale=-1.0, bias=1.0)
        total = _nn.elementwise_add(
            _nn.elementwise_mul(total, alive_m),
            _nn.elementwise_mul(
                _nn.elementwise_add(cum, self._noend_mask), fin))
        # [B, W*V] -> top-W
        flat = _nn.reshape(total, [self._batch, self.beam * self.vocab])
        top_p, top_i = _nn.topk(flat, self.beam)
        parent = _tensor.cast(
            _nn.elementwise_floordiv(
                top_i, _tensor.fill_constant([1], top_i.dtype, self.vocab)),
            "int64")  # [B, W]
        token = _nn.elementwise_mod(
            top_i, _tensor.fill_constant([1], top_i.dtype, self.vocab))
        self._log_probs = _nn.reshape(top_p, [self._batch * self.beam])
        # reorder states by parent beam
        offset = _tensor.assign(
            (np.arange(self._batch, dtype="int64") * self.beam).reshape(-1, 1))
        gidx = _nn.reshape(
            _nn.elementwise_add(parent, _nn.expand_as(offset, parent)),
            [self._batch * self.beam])
        new_states = [_nn.gather(s, gidx) for s in cell_states]
        token_flat = _nn.reshape(token, [self._batch * self.beam])
        finished = _tensor.cast(
            _tensor.equal(
                token_flat,
                _tensor.fill_constant([self._batch * self.beam], "int64",
                                      self.end)),
            "float32")
        self._finished = finished
        # outputs carry (token, parent) for gather_tree
        out = _nn.stack([token_flat,
                         _nn.reshape(parent, [self._batch * self.beam])], axis=1)
        return (out, token_flat), new_states, self.embed(token_flat), finished


def beam_search_decode(ids, parents, beam_size=None, end_id=None, name=None):
    """Backtrace stacked per-step (ids, parents) into full sequences via
    gather_tree (replaces the reference's LoD-array walk,
    beam_search_decode_op.cc)."""
    from . import sequence as _seq

    return _seq.gather_tree(ids, parents)


# ---------------------------------------------------------------------------
# single-step units + conveniences (reference nn.py lstm_unit / gru_unit)
# ---------------------------------------------------------------------------


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    cell = LSTMCell(hidden_t_prev.shape[-1], param_attr=param_attr,
                    bias_attr=bias_attr, forget_bias=forget_bias,
                    name=name or "lstm_unit")
    h, (new_h, new_c) = cell.call(x_t, [hidden_t_prev, cell_t_prev])
    return new_h, new_c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False, name=None):
    """One GRU step over a PRE-PROJECTED input (reference rnn.py:2724
    gru_unit + operators/gru_unit_op.h): `input` is [N, 3D] = x already
    passed through a size-3D fc; the op owns only the recurrent weight
    [D, 3D] (W_uh | W_rh in the first [D, 2D], W_ch last) and an optional
    [1, 3D] bias. Returns (hidden [N, D], reset_hidden_pre [N, D],
    gate [N, 3D] = the activated (u | r | c) slots)."""
    acts = {"identity": lambda v: v, "sigmoid": _ops.sigmoid,
            "tanh": _ops.tanh, "relu": _ops.relu}
    act_c = acts[activation]
    act_g = acts[gate_activation]
    d = size // 3
    helper = LayerHelper(name or "gru_unit")
    weight = helper.create_parameter(param_attr, shape=[d, 3 * d],
                                     dtype="float32")
    g_in = input
    if bias_attr is not False:
        bias = helper.create_parameter(bias_attr, shape=[1, 3 * d],
                                       dtype="float32", is_bias=True)
        g_in = _nn.elementwise_add(g_in, bias)
    x_ur = _nn.slice(g_in, axes=[1], starts=[0], ends=[2 * d])
    x_c = _nn.slice(g_in, axes=[1], starts=[2 * d], ends=[3 * d])
    # the reference op partitions the FLAT weight buffer (gru_unit_op.h
    # GEMM with ldb=2D): W_uh|W_rh = first 2*D*D elements as [D, 2D],
    # W_ch = the last D*D as [D, D] — NOT column slices of [D, 3D]
    w_flat = _nn.reshape(weight, [3 * d * d])
    w_ur = _nn.reshape(
        _nn.slice(w_flat, axes=[0], starts=[0], ends=[2 * d * d]), [d, 2 * d])
    w_c = _nn.reshape(
        _nn.slice(w_flat, axes=[0], starts=[2 * d * d], ends=[3 * d * d]),
        [d, d])
    ur = act_g(_nn.elementwise_add(x_ur, _nn.matmul(hidden, w_ur)))
    u = _nn.slice(ur, axes=[1], starts=[0], ends=[d])
    r = _nn.slice(ur, axes=[1], starts=[d], ends=[2 * d])
    reset_hidden_pre = _nn.elementwise_mul(r, hidden)
    c = act_c(_nn.elementwise_add(x_c, _nn.matmul(reset_hidden_pre, w_c)))
    if origin_mode:
        # h = u*h_prev + (1-u)*c  (Cho et al. 2014)
        new_h = _nn.elementwise_add(
            _nn.elementwise_mul(u, hidden),
            _nn.elementwise_mul(_nn.scale(u, scale=-1.0, bias=1.0), c),
        )
    else:
        # h = (1-u)*h_prev + u*c  (Chung et al. 2014)
        new_h = _nn.elementwise_add(
            _nn.elementwise_mul(_nn.scale(u, scale=-1.0, bias=1.0), hidden),
            _nn.elementwise_mul(u, c),
        )
    gate = _tensor.concat([u, r, c], axis=1)
    return new_h, reset_hidden_pre, gate


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers=1,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer LSTM over [B, T, D] (reference nn.py lstm /
    cudnn_lstm_op.cc, including `is_bidirec`). init_h/init_c:
    [num_layers * num_directions, B, H], direction-major per layer like
    cuDNN (layer0-fw, layer0-bw, layer1-fw, ...). Bidirectional layers
    run a reverse-scanned second cell and concat the two direction
    outputs on the feature dim — the cuDNN kernel's semantics expressed
    as two lax scans."""
    ndir = 2 if is_bidirec else 1

    def state0(buf, idx):
        return _nn.reshape(
            _nn.slice(buf, axes=[0], starts=[idx], ends=[idx + 1]),
            [buf.shape[1], hidden_size])

    out = input
    last_h, last_c = [], []
    for layer in range(num_layers):
        base = f"{name or 'lstm'}_l{layer}"
        cell_f = LSTMCell(hidden_size,
                          name=base if ndir == 1 else f"{base}_fw")
        h0 = state0(init_h, ndir * layer)
        c0 = state0(init_c, ndir * layer)
        out_f, (h, c) = _rnn_with_final(cell_f, out, [h0, c0])
        last_h.append(h)
        last_c.append(c)
        if is_bidirec:
            cell_b = LSTMCell(hidden_size, name=f"{base}_bw")
            h0b = state0(init_h, ndir * layer + 1)
            c0b = state0(init_c, ndir * layer + 1)
            out_b, (hb, cb) = _rnn_with_final(
                cell_b, out, [h0b, c0b], is_reverse=True)
            last_h.append(hb)
            last_c.append(cb)
            out = _tensor.concat([out_f, out_b], axis=2)
        else:
            out = out_f
        if dropout_prob > 0.0 and not is_test and layer < num_layers - 1:
            out = _nn.dropout(out, dropout_prob)
    return out, _nn.stack(last_h, axis=0), _nn.stack(last_c, axis=0)


def _rnn_with_final(cell, inputs, states, is_reverse=False):
    """rnn() now surfaces the true final (h, c) states."""
    outputs, final = rnn(cell, inputs, states, is_reverse=is_reverse)
    return outputs, (final[0], final[1])


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with a projection layer on the hidden state (reference nn.py
    dynamic_lstmp): h_proj = act(W_p @ h)."""
    from .sequence import dynamic_lstm

    hidden, cell = dynamic_lstm(
        input, size, param_attr=param_attr, bias_attr=bias_attr,
        use_peepholes=use_peepholes, is_reverse=is_reverse,
        gate_activation=gate_activation, cell_activation=cell_activation,
        candidate_activation=candidate_activation, dtype=dtype, name=name)
    proj = _nn.fc(hidden, proj_size, num_flatten_dims=2,
                  param_attr=ParamAttr(name=f"{name or 'lstmp'}.proj.w_0"),
                  bias_attr=False, act=proj_activation)
    return proj, cell
