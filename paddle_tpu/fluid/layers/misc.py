"""Breadth layers: activations, selection, randomness, metrics, misc.

Parity surface: reference python/paddle/fluid/layers/nn.py + tensor.py
entries — selu, brelu, soft_relu, stanh, multiplex, rank, size, sum,
scatter_nd, unique, unique_with_counts, is_empty, hash, shard_index,
sampling_id, gaussian_random(+batch_size_like), uniform_random(+bsl),
mean_iou, bilinear_tensor_product, add_position_encoding, fsp_matrix,
auc, chunk_eval, autoincreased_step_counter, get_tensor_from_selected_rows,
merge_selected_rows.
"""
from __future__ import annotations

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from . import nn as _nn
from . import tensor as _tensor


def _simple(op_type, x, attrs=None, out_slot="Out", in_slot="X", name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={in_slot: [x]},
                     outputs={out_slot: [out]}, attrs=attrs or {})
    return out


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _simple("selu", x, {"scale": scale, "alpha": alpha}, name=name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple("brelu", x, {"t_min": t_min, "t_max": t_max}, name=name)


def soft_relu(x, threshold=40.0, name=None):
    return _simple("soft_relu", x, {"threshold": threshold}, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple("stanh", x, {"scale_a": scale_a, "scale_b": scale_b},
                   name=name)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def rank(input):
    """Static rank as a constant tensor (reference rank)."""
    return _tensor.fill_constant([1], "int32", len(input.shape))


def size(input):
    """Static element count as a constant tensor (reference size)."""
    return _tensor.fill_constant([1], "int64", int(np.prod(input.shape)))


def sum(x):
    """Elementwise sum of a tensor list (reference sum op layer)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    helper = LayerHelper("sum")
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(xs)},
                     outputs={"Out": [out]})
    return out


def scatter_nd(index, updates, shape, name=None):
    """scatter_nd_add onto zeros (the reference defines it exactly so)."""
    zeros = _tensor.fill_constant(list(shape), updates.dtype, 0.0)
    return _nn.scatter_nd_add(zeros, index, updates)


def unique(x, dtype="int32"):
    """Static-shape unique: Out is x-sized (unique prefix then padding),
    plus Index (inverse map) and a scalar count — slice host-side with
    the count (XLA cannot return data-dependent shapes)."""
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    cnt = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "UniqueCount": [cnt]})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    count = helper.create_variable_for_type_inference(dtype)
    cnt = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "Count": [count], "UniqueCount": [cnt]})
    return out, index, count


def is_empty(x, cond=None):
    """Static emptiness as a constant bool (shapes are static on TPU)."""
    val = int(np.prod(x.shape)) == 0
    out = _tensor.fill_constant([1], "bool", val)
    if cond is not None:
        _tensor.assign(out, output=cond)
    return out


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="hash", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"mod_by": int(hash_size),
                            "num_hash": int(num_hash)})
    return out


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    helper = LayerHelper("shard_index")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="shard_index", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"index_num": index_num, "nshards": nshards,
                            "shard_id": shard_id,
                            "ignore_value": ignore_value})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    from .nn import _rng_salt_counter

    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference("int64")
    _rng_salt_counter[0] += 1
    helper.append_op(type="sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"rng_salt": _rng_salt_counter[0] + seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gaussian_random", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": float(mean),
                            "std": float(std), "seed": seed, "dtype": dtype})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="uniform_random", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "min": float(min),
                            "max": float(max), "seed": seed, "dtype": dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return gaussian_random(shape, mean, std, seed, dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return uniform_random(shape, dtype, min, max, seed)


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou", inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": int(num_classes)},
    )
    return miou, wrong, correct


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(
        helper.param_attr, shape=[size, x.shape[-1], y.shape[-1]], dtype=dtype
    )
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[1, size],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def add_position_encoding(input, alpha, beta, name=None):
    """x*alpha + beta*sinusoid position encoding (reference
    add_position_encoding_op.cc) — emitted as a constant table + ops."""
    b, t, d = input.shape
    half = d // 2
    pos = np.arange(t, dtype=np.float32)[:, None]
    inv = 1.0 / np.power(10000.0, np.arange(half, dtype=np.float32) / half)
    table = np.zeros((t, d), np.float32)
    table[:, :half] = np.sin(pos * inv[None, :])
    table[:, half:2 * half] = np.cos(pos * inv[None, :])
    enc = _tensor.assign(table)
    enc3 = _nn.reshape(enc, [1, t, d])
    return _nn.elementwise_add(
        _nn.scale(input, scale=float(alpha)),
        _nn.scale(_nn.expand_as(enc3, input), scale=float(beta)),
    )


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix for distillation (reference
    fsp_op.cc): [N, Cx, Cy] = x·y^T over flattened H*W, normalized."""
    n, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    hw = int(np.prod(x.shape[2:]))
    xf = _nn.reshape(x, [n, cx, hw])
    yf = _nn.reshape(y, [n, cy, hw])
    prod = _nn.matmul(xf, _nn.transpose(yf, [0, 2, 1]))
    return _nn.scale(prod, scale=1.0 / hw)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int32 step counter incremented every execution
    (reference layers/nn.py autoincreased_step_counter; int32 is exact to
    2^31 steps — see fluid/optimizer.py note on x64)."""
    from ..framework import default_main_program
    from ..optimizer import _create_persistable_var

    name = counter_name or "@STEP_COUNTER@"
    mb = default_main_program().global_block()
    if name in mb.vars:
        counter = mb.var(name)
    else:
        counter = _create_persistable_var(name, (1,), "int32",
                                          float(begin - 1))
    helper = LayerHelper("increment")
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows do not exist on TPU (sparse grads are dense
    scatter-adds, framework.py:33); identity for API compatibility."""
    return _tensor.assign(x)


def merge_selected_rows(x, name=None):
    """See get_tensor_from_selected_rows: identity on the dense analog."""
    return _tensor.assign(x)
