"""Breadth layers: activations, selection, randomness, metrics, misc.

Parity surface: reference python/paddle/fluid/layers/nn.py + tensor.py
entries — selu, brelu, soft_relu, stanh, multiplex, rank, size, sum,
scatter_nd, unique, unique_with_counts, is_empty, hash, shard_index,
sampling_id, gaussian_random(+batch_size_like), uniform_random(+bsl),
mean_iou, bilinear_tensor_product, add_position_encoding, fsp_matrix,
auc, chunk_eval, autoincreased_step_counter, get_tensor_from_selected_rows,
merge_selected_rows.
"""
from __future__ import annotations

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from . import nn as _nn
from . import tensor as _tensor

__all__ = [
    "selu", "brelu", "soft_relu", "stanh",
    "multiplex", "rank", "size", "sum",
    "scatter_nd", "unique", "unique_with_counts", "is_empty",
    "hash", "shard_index", "sampling_id", "gaussian_random",
    "uniform_random", "gaussian_random_batch_size_like", "uniform_random_batch_size_like", "mean_iou",
    "bilinear_tensor_product", "add_position_encoding", "fsp_matrix", "autoincreased_step_counter",
    "get_tensor_from_selected_rows", "merge_selected_rows", "auc", "chunk_eval",
    "nce", "hsigmoid", "inplace_abn", "similarity_focus",
    "continuous_value_model", "filter_by_instag", "py_reader", "create_py_reader_by_data",
    "read_file", "double_buffer", "load", "precision_recall",
]


def _simple(op_type, x, attrs=None, out_slot="Out", in_slot="X", name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={in_slot: [x]},
                     outputs={out_slot: [out]}, attrs=attrs or {})
    return out


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _simple("selu", x, {"scale": scale, "alpha": alpha}, name=name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple("brelu", x, {"t_min": t_min, "t_max": t_max}, name=name)


def soft_relu(x, threshold=40.0, name=None):
    return _simple("soft_relu", x, {"threshold": threshold}, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple("stanh", x, {"scale_a": scale_a, "scale_b": scale_b},
                   name=name)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def rank(input):
    """Static rank as a constant tensor (reference rank)."""
    return _tensor.fill_constant([1], "int32", len(input.shape))


def size(input):
    """Static element count as a constant tensor (reference size)."""
    return _tensor.fill_constant([1], "int64", int(np.prod(input.shape)))


def sum(x):
    """Elementwise sum of a tensor list (reference sum op layer)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    helper = LayerHelper("sum")
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(xs)},
                     outputs={"Out": [out]})
    return out


def scatter_nd(index, updates, shape, name=None):
    """scatter_nd_add onto zeros (the reference defines it exactly so)."""
    zeros = _tensor.fill_constant(list(shape), updates.dtype, 0.0)
    return _nn.scatter_nd_add(zeros, index, updates)


def unique(x, dtype="int32"):
    """Static-shape unique: Out is x-sized (unique prefix then padding),
    plus Index (inverse map) and a scalar count — slice host-side with
    the count (XLA cannot return data-dependent shapes)."""
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    cnt = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "UniqueCount": [cnt]})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    count = helper.create_variable_for_type_inference(dtype)
    cnt = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "Count": [count], "UniqueCount": [cnt]})
    return out, index, count


def is_empty(x, cond=None):
    """Static emptiness as a constant bool (shapes are static on TPU)."""
    val = int(np.prod(x.shape)) == 0
    out = _tensor.fill_constant([1], "bool", val)
    if cond is not None:
        _tensor.assign(out, output=cond)
    return out


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="hash", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"mod_by": int(hash_size),
                            "num_hash": int(num_hash)})
    return out


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    helper = LayerHelper("shard_index")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="shard_index", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"index_num": index_num, "nshards": nshards,
                            "shard_id": shard_id,
                            "ignore_value": ignore_value})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    from .nn import _rng_salt_counter

    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference("int64")
    _rng_salt_counter[0] += 1
    helper.append_op(type="sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"rng_salt": _rng_salt_counter[0] + seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gaussian_random", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": float(mean),
                            "std": float(std), "seed": seed, "dtype": dtype})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="uniform_random", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "min": float(min),
                            "max": float(max), "seed": seed, "dtype": dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return gaussian_random(shape, mean, std, seed, dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return uniform_random(shape, dtype, min, max, seed)


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou", inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": int(num_classes)},
    )
    return miou, wrong, correct


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(
        helper.param_attr, shape=[size, x.shape[-1], y.shape[-1]], dtype=dtype
    )
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[1, size],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def add_position_encoding(input, alpha, beta, name=None):
    """x*alpha + beta*sinusoid position encoding (reference
    add_position_encoding_op.cc) — emitted as a constant table + ops."""
    b, t, d = input.shape
    half = d // 2
    pos = np.arange(t, dtype=np.float32)[:, None]
    inv = 1.0 / np.power(10000.0, np.arange(half, dtype=np.float32) / half)
    table = np.zeros((t, d), np.float32)
    table[:, :half] = np.sin(pos * inv[None, :])
    table[:, half:2 * half] = np.cos(pos * inv[None, :])
    enc = _tensor.assign(table)
    enc3 = _nn.reshape(enc, [1, t, d])
    return _nn.elementwise_add(
        _nn.scale(input, scale=float(alpha)),
        _nn.scale(_nn.expand_as(enc3, input), scale=float(beta)),
    )


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix for distillation (reference
    fsp_op.cc): [N, Cx, Cy] = x·y^T over flattened H*W, normalized."""
    n, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    hw = int(np.prod(x.shape[2:]))
    xf = _nn.reshape(x, [n, cx, hw])
    yf = _nn.reshape(y, [n, cy, hw])
    prod = _nn.matmul(xf, _nn.transpose(yf, [0, 2, 1]))
    return _nn.scale(prod, scale=1.0 / hw)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int32 step counter incremented every execution
    (reference layers/nn.py autoincreased_step_counter; int32 is exact to
    2^31 steps — see fluid/optimizer.py note on x64)."""
    from ..framework import default_main_program
    from ..optimizer import _create_persistable_var

    name = counter_name or "@STEP_COUNTER@"
    mb = default_main_program().global_block()
    if name in mb.vars:
        counter = mb.var(name)
    else:
        counter = _create_persistable_var(name, (1,), "int32",
                                          float(begin - 1))
    helper = LayerHelper("increment")
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows do not exist on TPU (sparse grads are dense
    scatter-adds, framework.py:33); identity for API compatibility."""
    return _tensor.assign(x)


def merge_selected_rows(x, name=None):
    """See get_tensor_from_selected_rows: identity on the dense analog."""
    return _tensor.assign(x)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming ROC-AUC layer (reference layers/metric_op.py auc over
    metrics/auc_op.cc): persistable stat buffers accumulate across runs.
    Returns (auc_value, [batch stat update outs])."""
    from ..optimizer import _create_persistable_var

    nt = int(num_thresholds)
    stat_pos = _create_persistable_var(
        f"auc_stat_pos_{unique_suffix()}", (nt + 1,), "float32", 0.0)
    stat_neg = _create_persistable_var(
        f"auc_stat_neg_{unique_suffix()}", (nt + 1,), "float32", 0.0)
    helper = LayerHelper("auc")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"num_thresholds": nt, "curve": curve},
    )
    return out, [stat_pos, stat_neg]


_suffix_counter = [0]


def unique_suffix():
    _suffix_counter[0] += 1
    return _suffix_counter[0]


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunking precision/recall/F1 (reference chunk_eval_op.cc, IOB/IOE/
    IOBES/plain schemes). Host-side metric: the chunk extraction runs as a
    py_func callback (eval-only op; no gradient), the TPU analog of the
    reference's CPU-only kernel."""
    import numpy as np

    from .control_flow import py_func

    scheme = chunk_scheme.lower()
    tag_counts = {"iob": 2, "ioe": 2, "iobes": 4, "plain": 1}
    if scheme not in tag_counts:
        raise ValueError(f"chunk_eval: unknown scheme {chunk_scheme}")
    n_tags = tag_counts[scheme]
    excluded = set(excluded_chunk_types or [])

    def _extract(seq, lens):
        chunks = set()
        for b in range(seq.shape[0]):
            ln = int(lens[b]) if lens is not None else seq.shape[1]
            start = None
            ctype = None
            for t in range(ln):
                tag = int(seq[b, t])
                # tags in [0, n_tags*num_chunk_types) encode (type, kind);
                # anything else (the O / outside tag included) is outside
                if tag < 0 or tag >= n_tags * num_chunk_types:
                    inside = False
                    tag_kind, tag_type = None, None
                else:
                    tag_kind = tag % n_tags if scheme != "plain" else 0
                    tag_type = tag // n_tags if scheme != "plain" else tag
                    inside = True
                # simple IOB-style chunk detection (B=0, I=1 within type)
                if scheme == "plain":
                    if inside and tag_type not in excluded:
                        chunks.add((b, t, t, tag_type))
                    continue
                is_begin = inside and tag_kind == 0
                is_inside = inside and tag_kind != 0
                if is_begin:
                    if start is not None:
                        chunks.add((b, start, t - 1, ctype))
                    start, ctype = t, tag_type
                elif not is_inside and start is not None:
                    chunks.add((b, start, t - 1, ctype))
                    start, ctype = None, None
                elif is_inside and (start is None or tag_type != ctype):
                    start, ctype = t, tag_type
            if start is not None:
                chunks.add((b, start, ln - 1, ctype))
        return {c for c in chunks if c[3] not in excluded}

    def _chunk_stats(inf, lab, lens=None):
        inf_chunks = _extract(inf, lens)
        lab_chunks = _extract(lab, lens)
        correct = len(inf_chunks & lab_chunks)
        p = correct / len(inf_chunks) if inf_chunks else 0.0
        r = correct / len(lab_chunks) if lab_chunks else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        # int32: x64 is disabled in JAX, so 64-bit callback results are
        # rejected; counts are far below 2^31
        return (np.float32([p]), np.float32([r]), np.float32([f1]),
                np.int32([len(inf_chunks)]), np.int32([len(lab_chunks)]),
                np.int32([correct]))

    helper = LayerHelper("chunk_eval")
    outs = [helper.create_variable_for_type_inference(dt)
            for dt in ("float32", "float32", "float32",
                       "int32", "int32", "int32")]
    for v, shape in zip(outs, [(1,)] * 6):
        v.shape = shape
    xs = [input, label] + ([seq_length] if seq_length is not None else [])
    py_func(
        (lambda i, l, s=None: _chunk_stats(i, l, s)), x=xs, out=outs)
    return tuple(outs)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference layers/nn.py nce over
    nce_op.cc). Per-row cost [N, 1]: -log sigmoid(s_pos)
    - sum_k log sigmoid(-s_negk); negatives drawn per run via the
    uniform_random op (runtime sampling like the reference's sampler)."""
    if sampler != "uniform" or custom_dist is not None:
        raise NotImplementedError("nce: only the uniform sampler")
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    num_neg = int(num_neg_samples or 10)
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[num_total_classes],
                                dtype=input.dtype, is_bias=True)
    lbl = _nn.reshape(label, [input.shape[0]])
    w_pos = _nn.gather(w, lbl)                      # [N, D]
    b_pos = _nn.reshape(_nn.gather(_nn.reshape(b, [num_total_classes, 1]),
                                   lbl), [input.shape[0], 1])
    s_pos = _nn.elementwise_add(
        _nn.reduce_sum(_nn.elementwise_mul(input, w_pos), dim=[-1],
                       keep_dim=True), b_pos)
    # negatives: one shared sample set per step (reference uniform sampler)
    neg_f = uniform_random([num_neg], min=0.0, max=float(num_total_classes),
                           seed=seed)
    neg_ids = _tensor.cast(_nn.elementwise_min(
        neg_f, _tensor.fill_constant([num_neg], "float32",
                                     num_total_classes - 1 + 0.5)), "int64")
    w_neg = _nn.gather(w, neg_ids)                  # [K, D]
    b_neg = _nn.reshape(_nn.gather(_nn.reshape(b, [num_total_classes, 1]),
                                   neg_ids), [1, num_neg])
    s_neg = _nn.elementwise_add(
        _nn.matmul(input, w_neg, transpose_y=True), b_neg)  # [N, K]
    from . import ops as _ops

    cost = _nn.elementwise_add(
        _ops.softplus(_nn.scale(s_pos, -1.0)),       # -log sigmoid(s_pos)
        _nn.reduce_sum(_ops.softplus(s_neg), dim=[-1], keep_dim=True),
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid (reference layers/nn.py hsigmoid over
    hierarchical_sigmoid_op.cc): a complete binary tree over classes
    (default) or custom per-class paths. Cost [N, 1]."""
    import numpy as np

    if is_custom or path_table is not None or path_code is not None:
        raise NotImplementedError("hsigmoid: default complete tree only")
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    n_inner = max(num_classes - 1, 1)
    w = helper.create_parameter(helper.param_attr, shape=[n_inner, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[n_inner],
                                dtype=input.dtype, is_bias=True)
    # static complete-binary-tree paths: internal node ids 0..C-2; leaf c
    # corresponds to heap index C-1+c; path walks to the root
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    table = np.zeros((num_classes, depth), np.int64)
    code = np.zeros((num_classes, depth), np.float32)
    valid = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        node = n_inner + c  # heap leaf
        d = 0
        while node > 0 and d < depth:
            parent = (node - 1) // 2
            table[c, d] = parent
            code[c, d] = 1.0 if node == 2 * parent + 2 else 0.0  # right=1
            valid[c, d] = 1.0
            node = parent
            d += 1
    lbl = _nn.reshape(label, [input.shape[0]])
    t_var = _tensor.assign(table)
    c_var = _tensor.assign(code)
    v_var = _tensor.assign(valid)
    rows_t = _nn.gather(t_var, lbl)      # [N, depth] inner-node ids
    rows_c = _nn.gather(c_var, lbl)      # [N, depth] 0/1 codes
    rows_v = _nn.gather(v_var, lbl)      # [N, depth] path mask
    w_path = _nn.gather(w, _nn.reshape(rows_t, [-1]))  # [N*depth, D]
    w_path = _nn.reshape(w_path, [input.shape[0], depth, dim])
    b_path = _nn.reshape(
        _nn.gather(_nn.reshape(b, [n_inner, 1]), _nn.reshape(rows_t, [-1])),
        [input.shape[0], depth])
    logits = _nn.elementwise_add(
        _nn.reduce_sum(
            _nn.elementwise_mul(w_path, _nn.unsqueeze(input, [1])), dim=[-1]),
        b_path)  # [N, depth]
    from . import ops as _ops

    # BCE per node: -log sigmoid(z) if code 1 (right) else -log sigmoid(-z)
    per_node = _nn.elementwise_add(
        _nn.elementwise_mul(rows_c, _ops.softplus(_nn.scale(logits, -1.0))),
        _nn.elementwise_mul(
            _nn.scale(rows_c, -1.0, bias=1.0), _ops.softplus(logits)),
    )
    cost = _nn.reduce_sum(_nn.elementwise_mul(per_node, rows_v),
                          dim=[-1], keep_dim=True)
    return cost


def inplace_abn(input, act=None, **bn_kwargs):
    """Activated batch norm (reference inplace_abn_op.cc): batch_norm +
    activation; "in-place" memory aliasing is XLA's job here."""
    out = _nn.batch_norm(input, **bn_kwargs)
    if act:
        helper = LayerHelper("inplace_abn", act=act)
        out = helper.append_activation(out)
    return out


def similarity_focus(input, axis, indexes, name=None):
    """Similarity-focus mask (reference similarity_focus_op.cc): for each
    selected channel index, mark each (row, col) whose value is that
    row/col's maximum across the channel slice."""
    if axis != 1:
        raise NotImplementedError("similarity_focus: axis=1 (NCHW) only")
    from . import tensor as _t

    n, c, h, wd = input.shape
    masks = []
    for idx in indexes:
        ch = _nn.reshape(
            _nn.slice(input, axes=[1], starts=[idx], ends=[idx + 1]),
            [n, h, wd])
        row_max = _nn.reduce_max(ch, dim=[2], keep_dim=True)
        col_max = _nn.reduce_max(ch, dim=[1], keep_dim=True)
        m = _nn.elementwise_max(
            _t.cast(_t.equal(ch, _nn.expand_as(row_max, ch)), input.dtype),
            _t.cast(_t.equal(ch, _nn.expand_as(col_max, ch)), input.dtype),
        )
        masks.append(m)
    mask = masks[0]
    for m in masks[1:]:
        mask = _nn.elementwise_max(mask, m)
    return _nn.expand_as(_nn.unsqueeze(mask, [1]), input)


def continuous_value_model(input, cvm, use_cvm=True):
    """CTR continuous-value feature handling (reference cvm_op.cc):
    use_cvm keeps the 2 leading show/click columns (log-transformed by
    the feed), otherwise drops them."""
    d = input.shape[-1]
    if use_cvm:
        return input
    return _nn.slice(input, axes=[len(input.shape) - 1], starts=[2], ends=[d])


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True):
    """Reference filter_by_instag_op.cc filters rows by tag membership —
    a data-dependent output size, which XLA cannot express; mask rows to
    zero instead (dense analog) and return the mask as "LoD"."""
    from . import tensor as _t

    raise NotImplementedError(
        "filter_by_instag: data-dependent row filtering is not expressible "
        "with static shapes; apply a 0/1 mask to rows instead"
    )


class _PyReaderHandle:
    """In-program reader shim (reference layers/io.py py_reader): holds
    the created data Variables and a GeneratorLoader; `read_file` yields
    the Variables, iteration yields feed dicts for Executor.run."""

    def __init__(self, vars_, loader):
        self.vars = vars_
        self.loader = loader

    def decorate_paddle_reader(self, reader, places=None):
        self.loader.set_sample_list_generator(reader, places)

    def decorate_sample_list_generator(self, reader, places=None):
        self.loader.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        self.loader.set_batch_generator(reader, places)

    decorate_tensor_provider = decorate_batch_generator

    def __iter__(self):
        return iter(self.loader)

    def start(self):  # legacy non-iterable protocol: no-op (iterable only)
        return None

    def reset(self):
        return None


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Reference layers/io.py py_reader: creates the feed Variables and a
    prefetching loader; the read ops of the reference are unnecessary —
    Executor.run feeds explicitly (whole-block XLA design)."""
    from ..reader import GeneratorLoader

    vars_ = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        vars_.append(_tensor.data(f"{name or 'py_reader'}_{i}", list(shape),
                                  dtype=dtype, append_batch_size=False))
    loader = GeneratorLoader(feed_list=vars_, capacity=capacity)
    return _PyReaderHandle(vars_, loader)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..reader import GeneratorLoader

    return _PyReaderHandle(
        list(feed_list), GeneratorLoader(feed_list=feed_list, capacity=capacity))


def read_file(reader):
    """Unpack a py_reader handle into its data Variables."""
    if isinstance(reader, _PyReaderHandle):
        return reader.vars if len(reader.vars) > 1 else reader.vars[0]
    raise TypeError("read_file expects the handle returned by py_reader")


def double_buffer(reader, place=None, name=None):
    """Device prefetch is the executor's job under XLA (async dispatch +
    donated buffers); pass-through for API parity."""
    return reader


def load(out, file_path, load_as_fp16=False):
    """Load a parameter value from a save_params/save_persistables .npy
    file into `out` at build time (reference load_op.cc semantics,
    host-side; format matches fluid.io's np.save writer)."""
    arr = np.load(file_path, allow_pickle=False)
    if load_as_fp16:
        arr = arr.astype(np.float16)
    _tensor.assign(np.asarray(arr), output=out)
    return out


def precision_recall(input, label, num_classes, weights=None):
    """Streaming multi-class precision/recall/F1 (the op behind the
    reference's fluid.metrics machinery, precision_recall_op.cc):
    input [N, 1] predicted class ids. Returns (batch_metrics [6],
    accum_metrics [6]) with persistable [C, 4] TP/FP/TN/FN states."""
    from ..optimizer import _create_persistable_var

    states = _create_persistable_var(
        f"precision_recall_states_{unique_suffix()}",
        (int(num_classes), 4), "float32", 0.0)
    helper = LayerHelper("precision_recall")
    batch = helper.create_variable_for_type_inference("float32")
    accum = helper.create_variable_for_type_inference("float32")
    ins = {"Indices": [input], "Labels": [label], "StatesInfo": [states]}
    if weights is not None:
        ins["Weights"] = [weights]
    helper.append_op(
        type="precision_recall", inputs=ins,
        outputs={"BatchMetrics": [batch], "AccumMetrics": [accum],
                 "AccumStatesInfo": [states]},
    )
    return batch, accum
