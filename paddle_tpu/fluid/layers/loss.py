"""Loss layers built as compositions over the op set.

Parity surface: reference python/paddle/fluid/layers/loss.py +
nn.py loss entries — mse_loss, dice_loss, bpr_loss, center_loss,
margin_rank_loss, rank_loss, npair_loss, sigmoid_focal_loss,
teacher_student_sigmoid_loss, sampled_softmax_with_cross_entropy.

TPU-native: every loss is emitted as ordinary ops and fused by XLA —
the reference's dedicated CUDA loss kernels (e.g.
sigmoid_focal_loss_op.cu) have no per-op analog here.
"""
from __future__ import annotations

from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from . import nn as _nn
from . import ops as _ops
from . import tensor as _tensor

__all__ = [
    "mse_loss", "dice_loss", "bpr_loss", "center_loss",
    "rank_loss", "margin_rank_loss", "npair_loss", "sigmoid_focal_loss",
    "teacher_student_sigmoid_loss", "sampled_softmax_with_cross_entropy",
]


def mse_loss(input, label):
    """mean((input - label)^2) (reference mse_loss)."""
    return _nn.reduce_mean(_nn.square_error_cost(input, label))


def dice_loss(input, label, epsilon=1e-5):
    """1 - 2|X∩Y|/(|X|+|Y|) over the trailing class dim (reference
    dice_loss): input [N, ..., C] probabilities, label [N, ..., 1] ids."""
    nclasses = input.shape[-1]
    one_hot = _nn.one_hot(_nn.squeeze(label, axes=[-1]), nclasses)
    reduce_dims = list(range(1, len(input.shape)))
    inter = _nn.reduce_sum(_nn.elementwise_mul(input, one_hot), dim=reduce_dims)
    union = _nn.elementwise_add(
        _nn.reduce_sum(input, dim=reduce_dims),
        _nn.reduce_sum(one_hot, dim=reduce_dims),
    )
    dice = _nn.elementwise_div(
        _nn.scale(inter, scale=2.0),
        _nn.scale(union, bias=epsilon),
    )
    return _nn.reduce_mean(_nn.scale(dice, scale=-1.0, bias=1.0))


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking loss (reference bpr_loss_op.cc):
    per-row [N, 1] of -mean over j != y of log(sigmoid(x_y - x_j))."""
    n = input.shape[-1]
    pos = _nn.reduce_sum(
        _nn.elementwise_mul(input, _nn.one_hot(_nn.squeeze(label, axes=[-1]), n)),
        dim=[-1], keep_dim=True,
    )
    diff = _nn.elementwise_sub(pos, input)  # [B, C]: x_y - x_j
    logsig = _nn.scale(
        _ops.softplus(_nn.scale(diff, scale=-1.0)), scale=-1.0
    )  # log(sigmoid(d)) = -softplus(-d)
    mask = _nn.scale(_nn.one_hot(_nn.squeeze(label, axes=[-1]), n),
                     scale=-1.0, bias=1.0)
    per_row = _nn.elementwise_div(
        _nn.reduce_sum(_nn.elementwise_mul(logsig, mask), dim=[-1], keep_dim=True),
        _tensor.fill_constant([1], input.dtype, float(n - 1)),
    )
    return _nn.scale(per_row, scale=-1.0)


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """Distance to per-class centers (reference center_loss_op.cc).

    The reference updates centers in-kernel at rate `alpha`, independent
    of the optimizer. TPU-native: the loss VALUE is 0.5*||x - c||^2 (per
    row, [N,1]) computed against stop-gradient centers, plus a zero-VALUE
    term alpha*0.5*||sg(x) - c||^2 - sg(same) that routes a gradient of
    alpha*(c - x) into the center table — centers then move at rate
    alpha * optimizer_lr without changing the reported loss."""
    helper = LayerHelper("center_loss", param_attr=param_attr)
    dtype = input.dtype
    d = input.shape[-1]
    centers = helper.create_parameter(
        ParamAttr(name=f"{helper.name}.centers"),
        shape=[num_classes, d], dtype=dtype,
        default_initializer=ConstantInitializer(0.0),
    )
    idx = _nn.squeeze(label, axes=[-1])
    picked = _nn.gather(centers, idx)
    picked_sg = _tensor.assign(picked)
    picked_sg.stop_gradient = True
    loss = _nn.scale(
        _nn.reduce_sum(_ops.square(_nn.elementwise_sub(input, picked_sg)),
                       dim=[-1], keep_dim=True),
        scale=0.5,
    )
    if update_center:
        x_sg = _tensor.assign(input)
        x_sg.stop_gradient = True
        cterm = _nn.scale(
            _nn.reduce_sum(_ops.square(_nn.elementwise_sub(x_sg, picked)),
                           dim=[-1], keep_dim=True),
            scale=0.5 * float(alpha),
        )
        cterm_sg = _tensor.assign(cterm)
        cterm_sg.stop_gradient = True
        loss = _nn.elementwise_sub(_nn.elementwise_add(loss, cterm), cterm_sg)
    return loss


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (reference rank_loss_op.cc):
    C = log(1 + e^{o}) - t*o with o = left - right."""
    o = _nn.elementwise_sub(left, right)
    return _nn.reduce_mean(
        _nn.elementwise_sub(_ops.softplus(o), _nn.elementwise_mul(label, o))
    )


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="margin_rank_loss",
        inputs={"Label": [label], "X1": [left], "X2": [right]},
        outputs={"Out": [out]},
        attrs={"margin": float(margin)},
    )
    return out


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (reference npair_loss composition)."""
    b = anchor.shape[0]
    labels = _nn.reshape(labels, [b, 1])
    eq = _tensor.cast(_tensor.equal(labels, _nn.transpose(labels, [1, 0])), anchor.dtype)
    target = _nn.elementwise_div(
        eq, _nn.reduce_sum(eq, dim=[1], keep_dim=True)
    )
    logits = _nn.matmul(anchor, positive, transpose_y=True)
    xent = _nn.softmax_with_cross_entropy(logits, target, soft_label=True)
    l2 = _nn.scale(
        _nn.elementwise_add(
            _nn.reduce_mean(_nn.reduce_sum(_ops.square(anchor), dim=[1])),
            _nn.reduce_mean(_nn.reduce_sum(_ops.square(positive), dim=[1])),
        ),
        scale=l2_reg * 0.25,
    )
    return _nn.elementwise_add(_nn.reduce_mean(xent), l2)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """Focal loss for class imbalance (reference sigmoid_focal_loss_op.cc):
    x [N, C] logits, label [N, 1] int (0 = background, class c -> c-1 is
    the positive column), fg_num [1] normalizer."""
    c = x.shape[-1]
    lbl = _nn.squeeze(label, axes=[-1])
    # one-hot over C+1 then drop column 0 (background): pos[n, c] = 1 iff
    # label[n] == c+1
    oh = _nn.one_hot(lbl, c + 1)
    pos = _nn.slice(oh, axes=[1], starts=[1], ends=[c + 1])
    p = _ops.sigmoid(x)
    ce_pos = _ops.softplus(_nn.scale(x, scale=-1.0))   # -log(sigmoid)
    ce_neg = _ops.softplus(x)                           # -log(1-sigmoid)
    w_pos = _nn.elementwise_pow(
        _nn.scale(p, scale=-1.0, bias=1.0),
        _tensor.fill_constant([1], x.dtype, gamma))
    w_neg = _nn.elementwise_pow(p, _tensor.fill_constant([1], x.dtype, gamma))
    loss = _nn.elementwise_add(
        _nn.elementwise_mul(
            _nn.elementwise_mul(pos, _nn.elementwise_mul(w_pos, ce_pos)),
            _tensor.fill_constant([1], x.dtype, alpha)),
        _nn.elementwise_mul(
            _nn.elementwise_mul(_nn.scale(pos, scale=-1.0, bias=1.0),
                                _nn.elementwise_mul(w_neg, ce_neg)),
            _tensor.fill_constant([1], x.dtype, 1.0 - alpha)),
    )
    fg = _nn.elementwise_max(
        _tensor.cast(fg_num, x.dtype), _tensor.fill_constant([1], x.dtype, 1.0)
    )
    return _nn.elementwise_div(loss, fg)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Distillation loss (reference teacher_student_sigmoid_loss_op.cc):
    z clipped, loss = log(1+exp(z)) - z*label_binary + z*label_frac terms;
    the 2020 kernel computes - (label <= 0 branch) — reproduced as its
    documented closed form: log(1+e^z) - z * teacher + z * (teacher - hard)
    simplifies to log(1+e^z) - z*label for labels in [0,1]."""
    z = _nn.clip(input, soft_max_lower_bound, soft_max_up_bound)
    return _nn.elementwise_sub(_ops.softplus(z), _nn.elementwise_mul(z, label))


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Softmax CE over the true class + uniformly sampled negatives
    (reference sampled_softmax_with_cross_entropy_op.cc, uniform sampler).
    Build-time sampling (one negative set per graph build): sampled ids
    are constants, so XLA sees a static gather."""
    import numpy as np

    c = logits.shape[-1]
    rng = np.random.RandomState(seed or 0)
    sampled = rng.randint(0, c, size=[num_samples]).astype("int64")
    samp_var = _tensor.assign(sampled)
    neg = _nn.gather(_nn.transpose(logits, [1, 0]), samp_var)  # [S, B]
    neg = _nn.transpose(neg, [1, 0])  # [B, S]
    pos = _nn.reduce_sum(
        _nn.elementwise_mul(logits, _nn.one_hot(_nn.squeeze(label, axes=[-1]), c)),
        dim=[-1], keep_dim=True,
    )  # [B, 1]
    if remove_accidental_hits:
        # mask sampled columns that equal the true label
        hit = _tensor.cast(
            _tensor.equal(
                _nn.expand_as(label, neg),
                _nn.expand_as(_nn.reshape(samp_var, [1, num_samples]), neg),
            ),
            logits.dtype,
        )
        neg = _nn.elementwise_sub(neg, _nn.scale(hit, scale=1e9))
    joined = _tensor.concat([pos, neg], axis=1)  # [B, 1+S]; true class = col 0
    zeros = _tensor.fill_constant([logits.shape[0], 1], "int64", 0)
    return _nn.softmax_with_cross_entropy(joined, zeros)
