"""Neural network layers (the `fluid.layers.*` DSL).

Parity surface: python/paddle/fluid/layers/nn.py (~15k LoC, ~300 functions)
in the reference. Each function appends ops via LayerHelper; semantics match
the reference's op defs while lowering happens through the JAX emitters.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from .. import framework
from ..dtypes import convert_dtype
from ..framework import Variable
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    """Fully-connected layer (reference layers/nn.py fc). Multiple inputs sum."""
    helper = LayerHelper(
        "fc", input=input, param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    inputs = helper.multiple_input()
    dtype = helper.input_dtype()
    mul_results = []
    param_attrs = helper.param_attr
    if not isinstance(param_attrs, list):
        param_attrs = [param_attrs] * len(inputs)
    for inp, pattr in zip(inputs, param_attrs):
        in_dims = inp.shape
        flat = int(np.prod([abs(d) for d in in_dims[num_flatten_dims:]]))
        w = helper.create_parameter(pattr, shape=[flat, size], dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]}
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """reference layers/nn.py embedding (lookup_table_v2). is_sparse is a
    no-op on TPU: the vjp grad is a fused scatter-add in XLA."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, shape=list(size), dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1
        if padding_idx is None
        else padding_idx
        if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table_v2",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={"padding_idx": padding_idx, "is_sparse": is_sparse},
    )
    return out


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper(
        "conv2d", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    if data_format == "NCHW":
        pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    else:
        pre_act = helper.append_bias_op(pre_bias, dim_start=3)
    return helper.append_activation(pre_act)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    helper = LayerHelper(
        "conv2d_transpose", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    num_channels = input.shape[1]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    if filter_size is None:
        if output_size is None:
            raise ValueError("either filter_size or output_size must be set")
        if isinstance(output_size, int):
            output_size = [output_size, output_size]
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h_in - 1) * stride[0] + 2 * padding[0] - 1) // dilation[0] + 1,
            (output_size[1] - (w_in - 1) * stride[1] + 2 * padding[1] - 1) // dilation[1] + 1,
        ]
    elif isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper("pool2d", name=name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False, name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size, "adaptive": True},
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    in_place=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=True,
    use_global_stats=False,
):
    helper = LayerHelper(
        "batch_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    channels = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        helper.param_attr,
        shape=[channels],
        dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        helper.bias_attr, shape=[channels], dtype=dtype, is_bias=True
    )
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, initializer=ConstantInitializer(0.0), trainable=False),
        shape=[channels],
        dtype=dtype,
    )
    mean.stop_gradient = True
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, initializer=ConstantInitializer(1.0), trainable=False),
        shape=[channels],
        dtype=dtype,
    )
    variance.stop_gradient = True
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper(
        "layer_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    norm_shape = list(input.shape[begin_norm_axis:])
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr,
            shape=norm_shape,
            dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            helper.bias_attr, shape=norm_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def group_norm(
    input, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
    data_layout="NCHW", name=None
):
    helper = LayerHelper(
        "group_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    channels = input.shape[1]
    inputs = {"X": [input]}
    if helper.param_attr is not False:
        s = helper.create_parameter(
            helper.param_attr, shape=[channels], dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[channels], dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr, bias_attr=bias_attr, name=name)
    dtype = input.dtype
    channels = input.shape[1]
    inputs = {"X": [input]}
    if helper.param_attr is not False:
        s = helper.create_parameter(
            helper.param_attr, shape=[channels], dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[channels], dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    sm = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    sv = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="instance_norm",
        inputs=inputs,
        outputs={"Y": [out], "SavedMean": [sm], "SavedVariance": [sv]},
        attrs={"epsilon": epsilon},
    )
    return out


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="softmax",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_softmax",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    axis=-1,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]},
    )
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    op_type = "one_hot" if (input.shape and input.shape[-1] == 1) else "one_hot_v2"
    helper.append_op(
        type=op_type,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    out.stop_gradient = True
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference("float32")
    correct = correct or helper.create_variable_for_type_inference("int32")
    total = total or helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    acc_out.stop_gradient = True
    return acc_out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


# ---------------------------------------------------------------------------
# elementwise / matmul / reduce wrappers
# ---------------------------------------------------------------------------


def _elementwise(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]},
            attrs={"axis": axis},
        )
        return helper.append_activation(out)

    layer.__name__ = op_type
    return layer


elementwise_add = _elementwise("elementwise_add")
elementwise_sub = _elementwise("elementwise_sub")
elementwise_mul = _elementwise("elementwise_mul")
elementwise_div = _elementwise("elementwise_div")
elementwise_min = _elementwise("elementwise_min")
elementwise_max = _elementwise("elementwise_max")
elementwise_pow = _elementwise("elementwise_pow")
elementwise_mod = _elementwise("elementwise_mod")
elementwise_floordiv = _elementwise("elementwise_floordiv")


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={
            "transpose_X": transpose_x,
            "transpose_Y": transpose_y,
            "alpha": float(alpha),
        },
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def _reduce(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is None:
            attrs = {"reduce_all": True, "keep_dim": keep_dim}
        else:
            if isinstance(dim, int):
                dim = [dim]
            attrs = {"dim": list(dim), "keep_dim": keep_dim}
        helper.append_op(
            type=op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce("reduce_sum")
reduce_mean = _reduce("reduce_mean")
reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")
reduce_prod = _reduce("reduce_prod")
reduce_all = _reduce("reduce_all")
reduce_any = _reduce("reduce_any")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="clip",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="clip_by_norm",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    nrm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="norm",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [nrm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


# ---------------------------------------------------------------------------
# shape manipulation wrappers
# ---------------------------------------------------------------------------


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": [int(s) for s in shape]},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="squeeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="unsqueeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="flatten2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": axis},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    axis = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        n_out = num
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype) for _ in range(n_out)]
    helper.append_op(
        type="split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": axis, "num": num, "sections": sections},
    )
    return outs


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        type="stack",
        inputs={"X": x},
        outputs={"Y": [out]},
        attrs={"axis": axis},
    )
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op(
        type="unstack",
        inputs={"X": [x]},
        outputs={"Y": outs},
        attrs={"axis": axis, "num": num},
    )
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand_as",
        inputs={"X": [x], "target_tensor": [target_tensor]},
        outputs={"Out": [out]},
    )
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather_nd",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", name=name)
    out = helper.create_variable_for_type_inference(ref.dtype)
    helper.append_op(
        type="scatter_nd_add",
        inputs={"X": [ref], "Index": [index], "Updates": [updates]},
        outputs={"Out": [out]},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(
    input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
    data_format="NCHW", name=None
):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pad2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "paddings": list(paddings),
            "mode": mode,
            "pad_value": float(pad_value),
            "data_format": data_format,
        },
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(type="shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="strided_slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "axes": list(axes),
            "starts": list(starts),
            "ends": list(ends),
            "strides": list(strides),
        },
    )
    return out


def where(condition, x=None, y=None):
    """paddle.where / fluid.layers.where — ternary select."""
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        type="label_smooth",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Out": [out], "Diff": [diff]},
        attrs={"sigma": sigma or 1.0},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out], "Residual": [residual]},
        attrs={"delta": float(delta)},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="kldiv_loss",
        inputs={"X": [x], "Target": [target]},
        outputs={"Loss": [out]},
        attrs={"reduction": reduction},
    )
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if axis is not None:
        attrs["axis"] = axis
    if exclusive is not None:
        attrs["exclusive"] = exclusive
    if reverse is not None:
        attrs["reverse"] = reverse
    helper.append_op(
        type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
    )
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        helper.param_attr,
        shape=alpha_shape,
        dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


_rng_salt_counter = [0]


def fused_multihead_attention(
    q, k, v, attn_bias=None, num_heads=1, dropout_prob=0.0, is_test=False,
    causal=False, name=None
):
    """Fused scaled-dot-product attention over head-interleaved [B,S,H]
    tensors (TPU: Pallas flash attention; see ops/attention.py). The
    reference gets this via graph fusion passes (multihead_matmul_fuse_pass);
    here it is a first-class op. causal=True masks future positions
    inside the kernel (block-level skipping of upper-triangular work)."""
    helper = LayerHelper("fused_multihead_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    _rng_salt_counter[0] += 1
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        inputs["BiasQK"] = [attn_bias]
    helper.append_op(
        type="fused_multihead_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "num_heads": num_heads,
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "causal": bool(causal),
            "rng_salt": _rng_salt_counter[0],
        },
    )
    return out


def moe_ffn(
    input,
    num_experts,
    expert_hidden,
    top_k=2,
    capacity_factor=1.25,
    act="gelu",
    param_attr=None,
    name=None,
):
    """Mixture-of-Experts FFN (ops/moe_ops.py): top-k router + capacity-
    bounded dispatch + per-expert 2-layer FFN, all dense einsums so GSPMD
    can shard the expert dim over an "ep" mesh axis
    (DistributedStrategy.expert_parallel). New TPU-era capability — the
    reference (2020) predates MoE.

    input: [B, S, H]. Returns (out [B, S, H], aux_loss [] scalar); add
    `aux_weight * aux_loss` to the training loss to keep experts balanced.
    """
    helper = LayerHelper("moe_ffn", input=input, param_attr=param_attr, name=name)
    dtype = helper.input_dtype()
    h = input.shape[-1]
    e, f = num_experts, expert_hidden

    def _param(suffix, shape, is_bias=False):
        attr = ParamAttr._to_attr(param_attr)
        # biases stay zero-init (LayerHelper default) regardless of the
        # caller's weight initializer, matching the dense-FFN fc path
        init = attr.initializer if (attr and not is_bias) else None
        attr = ParamAttr(name=f"{name or helper.name}_{suffix}", initializer=init)
        return helper.create_parameter(attr, shape=shape, dtype=dtype, is_bias=is_bias)

    gate_w = _param("gate.w_0", [h, e])
    w1 = _param("expert.w1", [e, h, f])
    b1 = _param("expert.b1", [e, f], is_bias=True)
    w2 = _param("expert.w2", [e, f, h])
    b2 = _param("expert.b2", [e, h], is_bias=True)

    out = helper.create_variable_for_type_inference(dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="moe_ffn",
        inputs={
            "X": [input], "GateW": [gate_w],
            "W1": [w1], "B1": [b1], "W2": [w2], "B2": [b2],
        },
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={
            "top_k": int(top_k),
            "capacity_factor": float(capacity_factor),
            "activation": act,
        },
    )
    return out, aux


def unique_name_layer():  # pragma: no cover - placeholder parity stub
    raise NotImplementedError


def cos_sim(X, Y, name=None):
    """Row-wise cosine similarity (reference layers cos_sim)."""
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(
        type="cos_sim", inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]},
    )
    return out
