"""Control-flow layers: cond, while_loop, Switch/case helpers.

Parity surface: /root/reference/python/paddle/fluid/layers/control_flow.py
(cond, while_loop, While, Switch, increment, array ops). The TPU build
SSA-ifies sub-blocks at graph-build time: captured outer variables are
collected as explicit op inputs so the emitters can lower to
lax.cond / lax.while_loop (compiler-friendly control flow; no per-step
scopes at runtime).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from .. import framework
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper


def _captured_inputs(blocks, exclude: Sequence[str] = ()) -> List[str]:
    """Var names read by ops in `blocks` (recursively through block attrs)
    but created outside them — the SSA captures. Unique-name generation
    guarantees no shadowing, so "created inside" == present in a traced
    block's var map."""
    inside = set()

    def collect_inside(blk):
        inside.update(blk.vars)
        for op in blk.ops:
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    collect_inside(a)

    for b in blocks:
        collect_inside(b)

    captured: List[str] = []
    seen = set(exclude) | inside

    def walk(blk):
        for op in blk.ops:
            for n in op.input_names():
                if n not in seen:
                    seen.add(n)
                    captured.append(n)
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    walk(a)

    for b in blocks:
        walk(b)
    return captured


def _as_var_list(x):
    if x is None:
        return []
    if isinstance(x, Variable):
        return [x]
    return list(x)


def cond(pred, true_fn: Optional[Callable] = None, false_fn: Optional[Callable] = None, name=None):
    """reference layers/control_flow.py cond -> HLO Conditional.

    true_fn/false_fn take no args and return a Variable or (nested) list of
    Variables with matching shapes/dtypes."""
    prog = default_main_program()

    true_block = prog._create_block()
    true_out = true_fn() if true_fn is not None else None
    prog._rollback()
    false_block = prog._create_block()
    false_out = false_fn() if false_fn is not None else None
    prog._rollback()

    t_list, f_list = _as_var_list(true_out), _as_var_list(false_out)
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"({len(t_list)} vs {len(f_list)})"
        )

    captured = _captured_inputs([true_block, false_block])
    helper = LayerHelper("cond", name=name)
    parent = prog.current_block()
    out_vars = [
        parent.create_var(
            shape=v.shape, dtype=v.dtype, stop_gradient=v.stop_gradient
        )
        for v in t_list
    ]
    inputs = {"Cond": [pred]}
    if captured:
        inputs["Input"] = captured
    parent.append_op(
        type="cond",
        inputs=inputs,
        outputs={"Out": out_vars},
        attrs={
            "true_block": true_block,
            "false_block": false_block,
            "true_out_names": [v.name for v in t_list],
            "false_out_names": [v.name for v in f_list],
            "captured_names": captured,
        },
        infer=False,  # shapes already copied from the true branch
    )
    if true_out is None:
        return None
    if isinstance(true_out, Variable):
        return out_vars[0]
    return out_vars


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence[Variable], is_test=False, name=None):
    """reference layers/control_flow.py while_loop -> HLO While.

    Carried state is exactly `loop_vars` (SSA: body returns the next
    values); captured outer vars are loop-invariant."""
    prog = default_main_program()
    loop_vars = list(loop_vars)
    loop_names = [v.name for v in loop_vars]

    cond_block = prog._create_block()
    c_out = cond_fn(*loop_vars)
    prog._rollback()
    body_block = prog._create_block()
    b_out = body_fn(*loop_vars)
    prog._rollback()

    b_list = _as_var_list(b_out)
    if len(b_list) != len(loop_vars):
        raise ValueError(
            f"while_loop body must return {len(loop_vars)} values, got {len(b_list)}"
        )

    captured = [
        n
        for n in _captured_inputs([cond_block, body_block])
        if n not in set(loop_names)
    ]
    parent = prog.current_block()
    out_vars = [
        parent.create_var(shape=v.shape, dtype=v.dtype, stop_gradient=True)
        for v in loop_vars
    ]
    inputs = {"LoopVars": loop_vars}
    if captured:
        inputs["Input"] = captured
    parent.append_op(
        type="while_loop",
        inputs=inputs,
        outputs={"Out": out_vars},
        attrs={
            "cond_block": cond_block,
            "body_block": body_block,
            "loop_var_names": loop_names,
            "cond_out_name": c_out.name,
            "body_out_names": [v.name for v in b_list],
            "captured_names": captured,
        },
        infer=False,
    )
    return out_vars


class Switch:
    """reference layers/control_flow.py Switch — sugar over nested cond.
    Usage:
        with Switch() as switch:
            with switch.case(cond1): ... assign to out ...
            with switch.default(): ...
    Only the assignment-free functional style is supported: each case body
    must write the SAME set of vars via layers.assign(x, out)."""

    def __init__(self, name=None):
        raise NotImplementedError(
            "Switch requires scope-mutation semantics; use layers.cond / "
            "layers.case instead (functional control flow)"
        )


def case(pred_fn_pairs, default=None, name=None):
    """reference layers.case: first matching pred wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is None:
        return cond(pred, fn, fn)
    return cond(pred, fn, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference layers.switch_case."""
    from . import tensor as tensor_layers

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    pairs = []
    for idx, fn in items:
        idx_var = tensor_layers.fill_constant([1], branch_index.dtype, float(idx))
        pairs.append((tensor_layers.equal(branch_index, idx_var), fn))
    return case(pairs, default=default or items[-1][1])
