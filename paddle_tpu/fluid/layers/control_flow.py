"""Control-flow layers: cond, while_loop, Switch/case helpers.

Parity surface: /root/reference/python/paddle/fluid/layers/control_flow.py
(cond, while_loop, While, Switch, increment, array ops). The TPU build
SSA-ifies sub-blocks at graph-build time: captured outer variables are
collected as explicit op inputs so the emitters can lower to
lax.cond / lax.while_loop (compiler-friendly control flow; no per-step
scopes at runtime).
"""
from __future__ import annotations

import contextlib as _contextlib
from typing import Callable, List, Optional, Sequence

from .. import framework
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper


def _captured_inputs(blocks, exclude: Sequence[str] = ()) -> List[str]:
    """Var names read by ops in `blocks` (recursively through block attrs)
    but created outside them — the SSA captures. Unique-name generation
    guarantees no shadowing, so "created inside" == present in a traced
    block's var map."""
    inside = set()

    def collect_inside(blk):
        inside.update(blk.vars)
        for op in blk.ops:
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    collect_inside(a)

    for b in blocks:
        collect_inside(b)

    captured: List[str] = []
    seen = set(exclude) | inside

    def walk(blk):
        for op in blk.ops:
            for n in op.input_names():
                if n not in seen:
                    seen.add(n)
                    captured.append(n)
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    walk(a)

    for b in blocks:
        walk(b)
    return captured


def _as_var_list(x):
    if x is None:
        return []
    if isinstance(x, Variable):
        return [x]
    return list(x)


def cond(pred, true_fn: Optional[Callable] = None, false_fn: Optional[Callable] = None, name=None):
    """reference layers/control_flow.py cond -> HLO Conditional.

    true_fn/false_fn take no args and return a Variable or (nested) list of
    Variables with matching shapes/dtypes."""
    prog = default_main_program()

    true_block = prog._create_block()
    true_out = true_fn() if true_fn is not None else None
    prog._rollback()
    false_block = prog._create_block()
    false_out = false_fn() if false_fn is not None else None
    prog._rollback()

    t_list, f_list = _as_var_list(true_out), _as_var_list(false_out)
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"({len(t_list)} vs {len(f_list)})"
        )

    captured = _captured_inputs([true_block, false_block])
    helper = LayerHelper("cond", name=name)
    parent = prog.current_block()
    out_vars = [
        parent.create_var(
            shape=v.shape, dtype=v.dtype, stop_gradient=v.stop_gradient
        )
        for v in t_list
    ]
    inputs = {"Cond": [pred]}
    if captured:
        inputs["Input"] = captured
    parent.append_op(
        type="cond",
        inputs=inputs,
        outputs={"Out": out_vars},
        attrs={
            "true_block": true_block,
            "false_block": false_block,
            "true_out_names": [v.name for v in t_list],
            "false_out_names": [v.name for v in f_list],
            "captured_names": captured,
        },
        infer=False,  # shapes already copied from the true branch
    )
    if true_out is None:
        return None
    if isinstance(true_out, Variable):
        return out_vars[0]
    return out_vars


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence[Variable], is_test=False, name=None):
    """reference layers/control_flow.py while_loop -> HLO While.

    Carried state is exactly `loop_vars` (SSA: body returns the next
    values); captured outer vars are loop-invariant."""
    prog = default_main_program()
    loop_vars = list(loop_vars)
    loop_names = [v.name for v in loop_vars]

    cond_block = prog._create_block()
    c_out = cond_fn(*loop_vars)
    prog._rollback()
    body_block = prog._create_block()
    b_out = body_fn(*loop_vars)
    prog._rollback()

    b_list = _as_var_list(b_out)
    if len(b_list) != len(loop_vars):
        raise ValueError(
            f"while_loop body must return {len(loop_vars)} values, got {len(b_list)}"
        )

    captured = [
        n
        for n in _captured_inputs([cond_block, body_block])
        if n not in set(loop_names)
    ]
    parent = prog.current_block()
    out_vars = [
        parent.create_var(shape=v.shape, dtype=v.dtype, stop_gradient=True)
        for v in loop_vars
    ]
    inputs = {"LoopVars": loop_vars}
    if captured:
        inputs["Input"] = captured
    parent.append_op(
        type="while_loop",
        inputs=inputs,
        outputs={"Out": out_vars},
        attrs={
            "cond_block": cond_block,
            "body_block": body_block,
            "loop_var_names": loop_names,
            "cond_out_name": c_out.name,
            "body_out_names": [v.name for v in b_list],
            "captured_names": captured,
        },
        infer=False,
    )
    return out_vars


class Switch:
    """reference layers/control_flow.py Switch — sugar over nested cond.
    Usage:
        with Switch() as switch:
            with switch.case(cond1): ... assign to out ...
            with switch.default(): ...
    Only the assignment-free functional style is supported: each case body
    must write the SAME set of vars via layers.assign(x, out)."""

    def __init__(self, name=None):
        raise NotImplementedError(
            "Switch requires scope-mutation semantics; use layers.cond / "
            "layers.case instead (functional control flow)"
        )


def case(pred_fn_pairs, default=None, name=None):
    """reference layers.case: first matching pred wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is None:
        return cond(pred, fn, fn)
    return cond(pred, fn, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference layers.switch_case."""
    from . import tensor as tensor_layers

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    pairs = []
    for idx, fn in items:
        idx_var = tensor_layers.fill_constant([1], branch_index.dtype, float(idx))
        pairs.append((tensor_layers.equal(branch_index, idx_var), fn))
    return case(pairs, default=default or items[-1][1])


class StaticRNN:
    """Block-based RNN builder (reference layers/control_flow.py
    StaticRNN over recurrent_op.cc). Usage:

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [B, T, D] -> [B, D]
            h = rnn.memory(init=h0)          # carried state
            nh = fluid.layers.fc(concat([x_t, h]), H, act="tanh")
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        out = rnn()                          # [B, T, H]

    The step block compiles to one lax.scan body (op type `recurrent`).
    """

    def __init__(self, name=None, is_reverse=False):
        self._prog = None
        self._block = None
        self._seq_inputs = []   # (outer var, block var)
        self._memories = []     # (init outer var, block var)
        self._updates = {}      # block mem name -> block new-value name
        self._outputs = []      # block vars
        self._done = False
        self._is_reverse = is_reverse

    @_contextlib.contextmanager
    def step(self):
        self._prog = framework.default_main_program()
        self._block = self._prog._create_block()
        try:
            yield
        finally:
            self._prog._rollback()
            self._done = True

    def _in_step(self):
        if self._block is None or self._done:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._in_step()
        if len(x.shape) < 2:
            raise ValueError(f"step input needs [B, T, ...], got {x.shape}")
        if self._seq_inputs and x.shape[1] != self._seq_inputs[0][0].shape[1]:
            raise ValueError(
                f"step inputs must share one sequence length: got "
                f"{x.shape[1]} vs {self._seq_inputs[0][0].shape[1]}"
            )
        v = self._block.create_var(
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype
        )
        v.stop_gradient = x.stop_gradient
        self._seq_inputs.append((x, v))
        return v

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               dtype="float32"):
        self._in_step()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            from . import tensor as tensor_layers

            # the init value is an OUTER input of the recurrence: build
            # its fill_constant in the parent block, not the step block
            self._prog._rollback()
            try:
                init = tensor_layers.fill_constant(
                    [batch_ref.shape[0]] + list(shape), dtype, init_value
                )
            finally:
                self._prog.current_block_idx = self._block.idx
        v = self._block.create_var(shape=init.shape, dtype=init.dtype)
        v.stop_gradient = False
        self._memories.append((init, v))
        return v

    def update_memory(self, mem, new):
        self._in_step()
        self._updates[mem.name] = new.name

    def output(self, *outputs):
        self._in_step()
        for o in outputs:
            self._outputs.append(o)

    step_output = output

    def __call__(self):
        if not self._done:
            raise RuntimeError("finish the `with rnn.step():` block first")
        if not self._outputs:
            raise ValueError("StaticRNN needs at least one step_output")
        for init, v in self._memories:
            if v.name not in self._updates:
                raise ValueError(f"memory {v.name!r} was never update_memory'd")
        prog = self._prog
        parent = prog.current_block()
        t = self._seq_inputs[0][0].shape[1] if self._seq_inputs else None
        if t is None:
            raise ValueError("StaticRNN needs at least one step_input")

        local = {v.name for _, v in self._seq_inputs}
        local |= {v.name for _, v in self._memories}
        captured = [
            n for n in _captured_inputs([self._block]) if n not in local
        ]
        out_vars = [
            parent.create_var(
                shape=(o.shape[0], t) + tuple(o.shape[1:]), dtype=o.dtype
            )
            for o in self._outputs
        ]
        state_vars = [
            parent.create_var(shape=v.shape, dtype=v.dtype)
            for _, v in self._memories
        ]
        inputs = {
            "StepInputs": [x for x, _ in self._seq_inputs],
            "Memories": [init for init, _ in self._memories],
        }
        if captured:
            inputs["Captured"] = captured
        parent.append_op(
            type="recurrent",
            inputs=inputs,
            outputs={"Out": out_vars, "FinalStates": state_vars},
            attrs={
                "step_block": self._block,
                "step_input_names": [v.name for _, v in self._seq_inputs],
                "memory_in_names": [v.name for _, v in self._memories],
                "memory_out_names": [
                    self._updates[v.name] for _, v in self._memories
                ],
                "step_output_names": [o.name for o in self._outputs],
                "captured_names": captured,
                "is_reverse": self._is_reverse,
                "__seq_len__": t,
            },
            infer=False,
        )
        # final memory values, in memory() declaration order — consumers
        # (layers.rnn) read these as the recurrence's final states
        self.final_states = state_vars
        return out_vars[0] if len(out_vars) == 1 else out_vars


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None,
            name=None):
    """Run a Python callable as an op (reference layers/nn.py py_func over
    py_func_op.cc). `out` is a Variable (or list) pre-created with the
    result shape/dtype (use program.current_block().create_var). When
    backward_func is given it receives (inputs..., out_grads...) and
    returns the input gradients; without it the outputs are
    non-differentiable."""
    import numpy as np

    xs = _as_var_list(x)
    outs = _as_var_list(out)
    skip = {
        v.name if isinstance(v, Variable) else str(v)
        for v in _as_var_list(skip_vars_in_backward_input)
    }
    skip_idx = [i for i, v in enumerate(xs) if v.name in skip]
    unknown = skip - {v.name for v in xs}
    if unknown:
        raise ValueError(
            f"skip_vars_in_backward_input names not among inputs: {sorted(unknown)}"
        )
    block = framework.default_main_program().current_block()
    for o in outs:
        if o.shape is None or o.dtype is None:
            raise ValueError(f"py_func out {o.name!r} needs static shape+dtype")
        if backward_func is None:
            o.stop_gradient = True
    block.append_op(
        type="py_func",
        inputs={"X": xs},
        outputs={"Out": outs},
        attrs={
            "pyfunc_fwd": func,
            "pyfunc_bwd": backward_func,
            "pyfunc_skip_idx": skip_idx,
            "pyfunc_out_meta": [
                (tuple(o.shape), str(np.dtype(o.dtype))) for o in outs
            ],
        },
        infer=False,
    )
    return out


class While:
    """Block-style while loop (reference layers/control_flow.py While over
    while_op.cc). Usage:

        i = layers.fill_constant([1], "int64", 0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            ... ops that assign new values to outer vars ...
            layers.assign(layers.less_than(i, limit), cond)

    TPU-native lowering: the reference mutates outer-scope vars in a
    per-iteration Scope; here every outer var WRITTEN inside the block
    (including `cond`) becomes a lax.while_loop carry — the same
    SSA-ification the functional layers.while_loop uses, reusing its op.
    """

    def __init__(self, cond, is_test=False, name=None):
        # is_test kept for reference-API parity; the lowering is identical
        self._cond = cond
        self._prog = None
        self._body = None

    @_contextlib.contextmanager
    def block(self):
        self._prog = framework.default_main_program()
        parent = self._prog.current_block()
        self._body = self._prog._create_block()
        try:
            yield
        finally:
            self._prog._rollback()
        body = self._body

        # loop carries: outer vars written inside the body (cond included)
        written, seen = [], set()
        for op in body.ops:
            for names in op.outputs.values():
                for n in names:
                    if n in seen:
                        continue
                    seen.add(n)
                    if n in body.vars:
                        continue  # block-local temp
                    if parent._find_var_recursive(n) is not None:
                        written.append(n)
        if self._cond.name not in written:
            raise ValueError(
                "While: the loop must update its cond var inside the block "
                "(layers.assign(new_cond, cond)), or it would never exit"
            )
        loop_vars = [parent._find_var_recursive(n) for n in written]
        cond_block = self._prog._create_block()
        self._prog._rollback()
        captured = [
            n for n in _captured_inputs([body]) if n not in set(written)
        ]
        inputs = {"LoopVars": loop_vars}
        if captured:
            inputs["Input"] = captured
        parent.append_op(
            type="while_loop",
            inputs=inputs,
            outputs={"Out": loop_vars},  # rebind the same outer vars
            attrs={
                "cond_block": cond_block,  # empty: cond is itself a carry
                "body_block": body,
                "loop_var_names": written,
                "cond_out_name": self._cond.name,
                "body_out_names": written,
                "captured_names": captured,
            },
            infer=False,
        )


class IfElse:
    """Per-row conditional (reference layers/control_flow.py IfElse):
    cond is a [N, 1] bool mask; true/false bodies transform the rows.

    TPU-native semantics: instead of physically splitting rows into two
    scopes (reference conditional_block pairs), BOTH branches compute on
    the full batch and rows are merged with where(cond) — dense compute,
    no dynamic shapes, identical results for the row-wise functions the
    API contracts."""

    def __init__(self, cond, name=None):
        self._cond = cond
        self._true_out = []
        self._false_out = []
        self._in_true = None

    @_contextlib.contextmanager
    def true_block(self):
        self._in_true = True
        try:
            yield
        finally:
            self._in_true = None

    @_contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        try:
            yield
        finally:
            self._in_true = None

    def input(self, x):
        if self._in_true is None:
            raise RuntimeError("IfElse.input() must be called inside a block")
        return x  # both branches see the full rows (dense lowering)

    def output(self, *outs):
        if self._in_true is None:
            raise RuntimeError("IfElse.output() must be called inside a block")
        (self._true_out if self._in_true else self._false_out).extend(outs)

    def __call__(self):
        from . import nn as _nn
        from . import tensor as _tensor

        if len(self._true_out) != len(self._false_out):
            raise ValueError(
                f"IfElse: true block produced {len(self._true_out)} outputs, "
                f"false block {len(self._false_out)} — they must match"
            )
        merged = []
        for t, f in zip(self._true_out, self._false_out):
            mask = _tensor.cast(self._cond, t.dtype)
            shape = [1] * len(t.shape)
            shape[0] = t.shape[0]
            mask = _nn.reshape(mask, shape)
            merged.append(
                _nn.elementwise_add(
                    _nn.elementwise_mul(t, mask),
                    _nn.elementwise_mul(
                        f, _nn.scale(mask, scale=-1.0, bias=1.0)),
                )
            )
        return merged


# ---------------------------------------------------------------------------
# tensor arrays: build-time Python lists (static graph, static indices)
# ---------------------------------------------------------------------------


class TensorArray(list):
    """Build-time array of Variables (reference LoDTensorArray). On TPU
    every shape/index is static, so the array is a Python list resolved
    at graph build; use layers.while_loop carries for loop-dependent
    state instead of dynamic array writes."""


def create_array(dtype):
    return TensorArray()


def _static_index(i):
    import numpy as np

    if isinstance(i, (int, np.integer)):
        return int(i)
    # a var is a usable build-time constant only when its SOLE writer in
    # the program is one fill_constant op — a counter that is later
    # incremented/assigned must be rejected, not folded to its init value
    if isinstance(i, framework.Variable):
        writers = [
            op
            for block in i.block.program.blocks
            for op in block.ops
            if any(i.name in names for names in op.outputs.values())
        ]
        if len(writers) == 1 and writers[0].type == "fill_constant":
            return int(writers[0].attr("value"))
    raise NotImplementedError(
        "array index must be a Python int or an unmodified fill_constant "
        "var (static graph indices are build-time on TPU); inside loops "
        "carry state through layers.while_loop instead"
    )


def array_write(x, i, array=None):
    if array is None:
        array = create_array(x.dtype)
    idx = _static_index(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    v = array[_static_index(i)]
    if v is None:
        raise ValueError("array_read of an unwritten slot")
    return v


def array_length(array):
    from . import tensor as _tensor

    return _tensor.fill_constant([1], "int64", len(array))


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    from . import nn as _nn
    from . import tensor as _tensor

    if not input:
        raise ValueError("tensor_array_to_tensor: empty array")
    vals = [v for v in input if v is not None]
    if use_stack:
        out = _nn.stack(vals, axis=axis)
    else:
        out = _tensor.concat(vals, axis=axis)
    sizes = _tensor.assign(
        __import__("numpy").asarray(
            [v.shape[axis] if not use_stack else 1 for v in vals], "int32")
    )
    return out, sizes


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Runtime tensor printing (reference print_op.cc) via jax.debug.print
    inside the compiled step."""
    helper = LayerHelper("print", name=None)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"message": message or "", "first_n": first_n,
               "summarize": summarize, "var_name": input.name},
    )
    return out


def Assert(cond, data=None, summarize=20, name=None):
    """Runtime assertion (reference assert_op.cc): aborts the step when
    cond is False, printing `data` tensors."""
    helper = LayerHelper("assert", name=name)
    inputs = {"Cond": [cond]}
    if data:
        inputs["Data"] = list(data)
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="assert", inputs=inputs, outputs={"Out": [out]},
                     attrs={"summarize": summarize})
    return out


class DynamicRNN:
    """Variable-length RNN over the padded+mask representation (reference
    layers/control_flow.py DynamicRNN over LoD): same step API as
    StaticRNN plus automatic length masking — memories freeze once a
    row's sequence ends, reproducing the reference's shrink-by-LoD
    behavior without ragged tensors.

        drnn = DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, length=lens)   # x: [B, T, D]
            h = drnn.memory(shape=[H], batch_ref=x)
            nh = layers.fc(layers.concat([x_t, h], 1), H, act="tanh")
            drnn.update_memory(h, nh)               # masked update
            drnn.output(nh)
        out = drnn()                                # [B, T, H]
    """

    def __init__(self, name=None):
        self._rnn = StaticRNN(name=name)
        self._mask_step = None  # [B, 1] validity for the current step
        self._length = None

    @_contextlib.contextmanager
    def block(self):
        with self._rnn.step():
            yield

    def step_input(self, x, length=None):
        v = self._rnn.step_input(x)
        if length is not None and self._mask_step is None:
            from . import sequence as _seq
            from . import tensor as _tensor

            self._length = length
            # [B, T, 1] mask built in the parent block, scanned per step
            prog = self._rnn._prog
            prog._rollback()
            try:
                mask = _seq.sequence_mask(length, maxlen=x.shape[1],
                                          dtype="float32")
                from . import nn as _nn

                mask3 = _nn.reshape(mask, [x.shape[0], x.shape[1], 1])
            finally:
                prog.current_block_idx = self._rnn._block.idx
            self._mask_step = self._rnn.step_input(mask3)
        return v

    def static_input(self, x):
        return x  # captured automatically by the step block

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               dtype="float32", need_reorder=False):
        return self._rnn.memory(init=init, shape=shape, batch_ref=batch_ref,
                                init_value=init_value, dtype=dtype)

    def update_memory(self, mem, new):
        if self._mask_step is not None:
            from . import nn as _nn

            m = self._mask_step
            if len(new.shape) > len(m.shape):
                m = _nn.reshape(
                    m, list(m.shape) + [1] * (len(new.shape) - len(m.shape)))
            new = _nn.elementwise_add(
                _nn.elementwise_mul(new, m),
                _nn.elementwise_mul(mem, _nn.scale(m, scale=-1.0, bias=1.0)),
            )
        self._rnn.update_memory(mem, new)

    def output(self, *outputs):
        # past-length steps emit zeros — the repo's padded+mask convention
        # (padding lives at the tail and is masked out; sequence_ops.py)
        if self._mask_step is not None:
            from . import nn as _nn

            masked = []
            for o in outputs:
                m = self._mask_step
                if len(o.shape) > len(m.shape):
                    m = _nn.reshape(
                        m, list(m.shape) + [1] * (len(o.shape) - len(m.shape)))
                masked.append(_nn.elementwise_mul(o, m))
            outputs = masked
        self._rnn.output(*outputs)

    def __call__(self):
        return self._rnn()
