"""Control-flow layers: cond, while_loop, Switch/case helpers.

Parity surface: /root/reference/python/paddle/fluid/layers/control_flow.py
(cond, while_loop, While, Switch, increment, array ops). The TPU build
SSA-ifies sub-blocks at graph-build time: captured outer variables are
collected as explicit op inputs so the emitters can lower to
lax.cond / lax.while_loop (compiler-friendly control flow; no per-step
scopes at runtime).
"""
from __future__ import annotations

import contextlib as _contextlib
from typing import Callable, List, Optional, Sequence

from .. import framework
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper


def _captured_inputs(blocks, exclude: Sequence[str] = ()) -> List[str]:
    """Var names read by ops in `blocks` (recursively through block attrs)
    but created outside them — the SSA captures. Unique-name generation
    guarantees no shadowing, so "created inside" == present in a traced
    block's var map."""
    inside = set()

    def collect_inside(blk):
        inside.update(blk.vars)
        for op in blk.ops:
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    collect_inside(a)

    for b in blocks:
        collect_inside(b)

    captured: List[str] = []
    seen = set(exclude) | inside

    def walk(blk):
        for op in blk.ops:
            for n in op.input_names():
                if n not in seen:
                    seen.add(n)
                    captured.append(n)
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    walk(a)

    for b in blocks:
        walk(b)
    return captured


def _as_var_list(x):
    if x is None:
        return []
    if isinstance(x, Variable):
        return [x]
    return list(x)


def cond(pred, true_fn: Optional[Callable] = None, false_fn: Optional[Callable] = None, name=None):
    """reference layers/control_flow.py cond -> HLO Conditional.

    true_fn/false_fn take no args and return a Variable or (nested) list of
    Variables with matching shapes/dtypes."""
    prog = default_main_program()

    true_block = prog._create_block()
    true_out = true_fn() if true_fn is not None else None
    prog._rollback()
    false_block = prog._create_block()
    false_out = false_fn() if false_fn is not None else None
    prog._rollback()

    t_list, f_list = _as_var_list(true_out), _as_var_list(false_out)
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"({len(t_list)} vs {len(f_list)})"
        )

    captured = _captured_inputs([true_block, false_block])
    helper = LayerHelper("cond", name=name)
    parent = prog.current_block()
    out_vars = [
        parent.create_var(
            shape=v.shape, dtype=v.dtype, stop_gradient=v.stop_gradient
        )
        for v in t_list
    ]
    inputs = {"Cond": [pred]}
    if captured:
        inputs["Input"] = captured
    parent.append_op(
        type="cond",
        inputs=inputs,
        outputs={"Out": out_vars},
        attrs={
            "true_block": true_block,
            "false_block": false_block,
            "true_out_names": [v.name for v in t_list],
            "false_out_names": [v.name for v in f_list],
            "captured_names": captured,
        },
        infer=False,  # shapes already copied from the true branch
    )
    if true_out is None:
        return None
    if isinstance(true_out, Variable):
        return out_vars[0]
    return out_vars


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence[Variable], is_test=False, name=None):
    """reference layers/control_flow.py while_loop -> HLO While.

    Carried state is exactly `loop_vars` (SSA: body returns the next
    values); captured outer vars are loop-invariant."""
    prog = default_main_program()
    loop_vars = list(loop_vars)
    loop_names = [v.name for v in loop_vars]

    cond_block = prog._create_block()
    c_out = cond_fn(*loop_vars)
    prog._rollback()
    body_block = prog._create_block()
    b_out = body_fn(*loop_vars)
    prog._rollback()

    b_list = _as_var_list(b_out)
    if len(b_list) != len(loop_vars):
        raise ValueError(
            f"while_loop body must return {len(loop_vars)} values, got {len(b_list)}"
        )

    captured = [
        n
        for n in _captured_inputs([cond_block, body_block])
        if n not in set(loop_names)
    ]
    parent = prog.current_block()
    out_vars = [
        parent.create_var(shape=v.shape, dtype=v.dtype, stop_gradient=True)
        for v in loop_vars
    ]
    inputs = {"LoopVars": loop_vars}
    if captured:
        inputs["Input"] = captured
    parent.append_op(
        type="while_loop",
        inputs=inputs,
        outputs={"Out": out_vars},
        attrs={
            "cond_block": cond_block,
            "body_block": body_block,
            "loop_var_names": loop_names,
            "cond_out_name": c_out.name,
            "body_out_names": [v.name for v in b_list],
            "captured_names": captured,
        },
        infer=False,
    )
    return out_vars


class Switch:
    """reference layers/control_flow.py Switch — sugar over nested cond.
    Usage:
        with Switch() as switch:
            with switch.case(cond1): ... assign to out ...
            with switch.default(): ...
    Only the assignment-free functional style is supported: each case body
    must write the SAME set of vars via layers.assign(x, out)."""

    def __init__(self, name=None):
        raise NotImplementedError(
            "Switch requires scope-mutation semantics; use layers.cond / "
            "layers.case instead (functional control flow)"
        )


def case(pred_fn_pairs, default=None, name=None):
    """reference layers.case: first matching pred wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is None:
        return cond(pred, fn, fn)
    return cond(pred, fn, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference layers.switch_case."""
    from . import tensor as tensor_layers

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    pairs = []
    for idx, fn in items:
        idx_var = tensor_layers.fill_constant([1], branch_index.dtype, float(idx))
        pairs.append((tensor_layers.equal(branch_index, idx_var), fn))
    return case(pairs, default=default or items[-1][1])


class StaticRNN:
    """Block-based RNN builder (reference layers/control_flow.py
    StaticRNN over recurrent_op.cc). Usage:

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [B, T, D] -> [B, D]
            h = rnn.memory(init=h0)          # carried state
            nh = fluid.layers.fc(concat([x_t, h]), H, act="tanh")
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        out = rnn()                          # [B, T, H]

    The step block compiles to one lax.scan body (op type `recurrent`).
    """

    def __init__(self, name=None, is_reverse=False):
        self._prog = None
        self._block = None
        self._seq_inputs = []   # (outer var, block var)
        self._memories = []     # (init outer var, block var)
        self._updates = {}      # block mem name -> block new-value name
        self._outputs = []      # block vars
        self._done = False
        self._is_reverse = is_reverse

    @_contextlib.contextmanager
    def step(self):
        self._prog = framework.default_main_program()
        self._block = self._prog._create_block()
        try:
            yield
        finally:
            self._prog._rollback()
            self._done = True

    def _in_step(self):
        if self._block is None or self._done:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._in_step()
        if len(x.shape) < 2:
            raise ValueError(f"step input needs [B, T, ...], got {x.shape}")
        if self._seq_inputs and x.shape[1] != self._seq_inputs[0][0].shape[1]:
            raise ValueError(
                f"step inputs must share one sequence length: got "
                f"{x.shape[1]} vs {self._seq_inputs[0][0].shape[1]}"
            )
        v = self._block.create_var(
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype
        )
        v.stop_gradient = x.stop_gradient
        self._seq_inputs.append((x, v))
        return v

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               dtype="float32"):
        self._in_step()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            from . import tensor as tensor_layers

            # the init value is an OUTER input of the recurrence: build
            # its fill_constant in the parent block, not the step block
            self._prog._rollback()
            try:
                init = tensor_layers.fill_constant(
                    [batch_ref.shape[0]] + list(shape), dtype, init_value
                )
            finally:
                self._prog.current_block_idx = self._block.idx
        v = self._block.create_var(shape=init.shape, dtype=init.dtype)
        v.stop_gradient = False
        self._memories.append((init, v))
        return v

    def update_memory(self, mem, new):
        self._in_step()
        self._updates[mem.name] = new.name

    def output(self, *outputs):
        self._in_step()
        for o in outputs:
            self._outputs.append(o)

    step_output = output

    def __call__(self):
        if not self._done:
            raise RuntimeError("finish the `with rnn.step():` block first")
        if not self._outputs:
            raise ValueError("StaticRNN needs at least one step_output")
        for init, v in self._memories:
            if v.name not in self._updates:
                raise ValueError(f"memory {v.name!r} was never update_memory'd")
        prog = self._prog
        parent = prog.current_block()
        t = self._seq_inputs[0][0].shape[1] if self._seq_inputs else None
        if t is None:
            raise ValueError("StaticRNN needs at least one step_input")

        local = {v.name for _, v in self._seq_inputs}
        local |= {v.name for _, v in self._memories}
        captured = [
            n for n in _captured_inputs([self._block]) if n not in local
        ]
        out_vars = [
            parent.create_var(
                shape=(o.shape[0], t) + tuple(o.shape[1:]), dtype=o.dtype
            )
            for o in self._outputs
        ]
        state_vars = [
            parent.create_var(shape=v.shape, dtype=v.dtype)
            for _, v in self._memories
        ]
        inputs = {
            "StepInputs": [x for x, _ in self._seq_inputs],
            "Memories": [init for init, _ in self._memories],
        }
        if captured:
            inputs["Captured"] = captured
        parent.append_op(
            type="recurrent",
            inputs=inputs,
            outputs={"Out": out_vars, "FinalStates": state_vars},
            attrs={
                "step_block": self._block,
                "step_input_names": [v.name for _, v in self._seq_inputs],
                "memory_in_names": [v.name for _, v in self._memories],
                "memory_out_names": [
                    self._updates[v.name] for _, v in self._memories
                ],
                "step_output_names": [o.name for o in self._outputs],
                "captured_names": captured,
                "is_reverse": self._is_reverse,
                "__seq_len__": t,
            },
            infer=False,
        )
        return out_vars[0] if len(out_vars) == 1 else out_vars


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None,
            name=None):
    """Run a Python callable as an op (reference layers/nn.py py_func over
    py_func_op.cc). `out` is a Variable (or list) pre-created with the
    result shape/dtype (use program.current_block().create_var). When
    backward_func is given it receives (inputs..., out_grads...) and
    returns the input gradients; without it the outputs are
    non-differentiable."""
    import numpy as np

    xs = _as_var_list(x)
    outs = _as_var_list(out)
    skip = {
        v.name if isinstance(v, Variable) else str(v)
        for v in _as_var_list(skip_vars_in_backward_input)
    }
    skip_idx = [i for i, v in enumerate(xs) if v.name in skip]
    unknown = skip - {v.name for v in xs}
    if unknown:
        raise ValueError(
            f"skip_vars_in_backward_input names not among inputs: {sorted(unknown)}"
        )
    block = framework.default_main_program().current_block()
    for o in outs:
        if o.shape is None or o.dtype is None:
            raise ValueError(f"py_func out {o.name!r} needs static shape+dtype")
        if backward_func is None:
            o.stop_gradient = True
    block.append_op(
        type="py_func",
        inputs={"X": xs},
        outputs={"Out": outs},
        attrs={
            "pyfunc_fwd": func,
            "pyfunc_bwd": backward_func,
            "pyfunc_skip_idx": skip_idx,
            "pyfunc_out_meta": [
                (tuple(o.shape), str(np.dtype(o.dtype))) for o in outs
            ],
        },
        infer=False,
    )
    return out
