"""paddle_tpu.fluid — static-graph front end.

Parity surface: python/paddle/fluid/__init__.py in the reference. The same
Program/Executor/layers/optimizer API, executing through whole-block XLA JIT.
"""
from . import (  # noqa: F401
    backward,
    clip,
    dtypes,
    dygraph,
    framework,
    initializer,
    io,
    layers,
    optimizer,
    param_attr,
    regularizer,
    unique_name,
)
from . import checkpoint, compiler, crypto, dataset, learning_rate_scheduler, metrics, monitor, nets, profiler, reader, transpiler  # noqa: F401
from .checkpoint import CheckpointManager  # noqa: F401
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from .reader import DataLoader, PyReader  # noqa: F401
from .backward import append_backward, calc_gradient, gradients  # noqa: F401

# reference exposes schedules under fluid.layers.* too
for _n in (
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
    "linear_lr_warmup",
):
    setattr(layers, _n, getattr(learning_rate_scheduler, _n))
del _n
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .framework import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    in_dygraph_mode,
    program_guard,
)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401


class CPUPlace:
    """Place tags kept for API parity; JAX/PJRT owns actual placement."""

    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# the reference's CUDAPlace maps to a TPU chip here
CUDAPlace = TPUPlace
XLAPlace = TPUPlace


class CUDAPinnedPlace:
    def __repr__(self):
        return "CUDAPinnedPlace"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    import jax

    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except RuntimeError:
        return False


def cuda_places(device_ids=None):
    return [TPUPlace(i) for i in (device_ids or [0])]


def cpu_places(device_count=None):
    return [CPUPlace()]


def device_count() -> int:
    import jax

    return jax.device_count()


# data layer (fluid.data in 1.8+)
def data(name, shape, dtype="float32", lod_level=0):
    return layers.tensor.data(
        name, shape, dtype, lod_level, append_batch_size=False
    )


def embedding(*args, **kwargs):
    return layers.embedding(*args, **kwargs)
