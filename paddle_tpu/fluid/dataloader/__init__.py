"""Map-style datasets + multiprocess batch loading.

Parity surface: /root/reference/python/paddle/fluid/dataloader/
(dataset.py, batch_sampler.py, dataloader_iter.py) behind
fluid.reader.DataLoader(dataset, ..., num_workers=N) (reader.py:112).

TPU-native design: the reference workers serialize LoDTensors into
shared-memory files consumed by a C++ blocking queue inside the program.
Here the executor feeds numpy dicts directly, so workers are plain
fork()ed processes that pull index-batches from an index queue, build
batches with the collate fn, and send them back over a multiprocessing
queue; the parent restores submission order so `num_workers=N` is
bit-identical to `num_workers=0`. Heavy per-sample decode (image aug,
tokenization) overlaps with the device step without fighting the GIL.
"""
from __future__ import annotations

import itertools
import queue as _queue
import traceback
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class Dataset:
    """Map-style dataset (reference dataloader/dataset.py): subclasses
    implement __getitem__ and __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError("Dataset subclasses must implement __getitem__")

    def __len__(self):
        raise NotImplementedError("Dataset subclasses must implement __len__")


class IterableDataset(Dataset):
    """Stream-style dataset: subclasses implement __iter__. Only
    num_workers=0 is supported (a stream cannot be index-sharded without
    consuming it); use GeneratorLoader.use_multiprocess for off-process
    streaming."""

    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset has no __getitem__; iterate it")

    def __len__(self):
        raise TypeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    """Wrap equal-length arrays; sample i is a tuple of row i of each."""

    def __init__(self, *arrays):
        if not arrays:
            raise ValueError("TensorDataset needs at least one array")
        self.arrays = [np.asarray(a) for a in arrays]
        n = len(self.arrays[0])
        if any(len(a) != n for a in self.arrays):
            raise ValueError("TensorDataset arrays must have equal length")

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self):
        return len(self.arrays[0])


class BatchSampler:
    """Yield lists of sample indices (reference dataloader/batch_sampler.py).

    Either wrap a dataset (batch_size/shuffle/drop_last) or a custom
    `sampler` iterable of indices.
    """

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False, seed: Optional[int] = None):
        if (dataset is None) == (sampler is None):
            raise ValueError("BatchSampler: pass exactly one of dataset / sampler")
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.sampler = sampler
        self.shuffle = shuffle
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self._seed = seed
        self._epoch = 0

    def _indices(self):
        if self.sampler is not None:
            return list(self.sampler)
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            seed = self._seed if self._seed is not None else self._epoch
            np.random.RandomState(seed).shuffle(idx)
            self._epoch += 1
        return idx.tolist()

    def __iter__(self):
        batch = []
        for i in self._indices():
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler) if self.sampler is not None else len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(samples: Sequence[Any]):
    """Stack each field of the sample tuples along axis 0."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return [np.stack([np.asarray(s[i]) for s in samples])
                for i in range(len(first))]
    return [np.stack([np.asarray(s) for s in samples])]


_WORKER_END = None  # index-queue sentinel


def _worker_loop(dataset, index_q, result_q, collate_fn, worker_init_fn, wid):
    """Child process body: pull (batch_no, indices), push (batch_no, arrays)."""
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
        while True:
            item = index_q.get()
            if item is _WORKER_END:
                return
            bno, indices = item
            try:
                batch = collate_fn([dataset[i] for i in indices])
                result_q.put((bno, [np.asarray(a) for a in batch]))
            except Exception:  # noqa: BLE001 — shipped to parent
                result_q.put(("error", f"worker {wid}:\n{traceback.format_exc()}"))
                return
    except KeyboardInterrupt:
        pass


def _spawn_safe(dataset, collate_fn, worker_init_fn) -> bool:
    """Spawn requires the worker args to pickle (fork inherits them) AND
    to be importable from the child: objects whose class/function lives
    in __main__ pickle fine by reference but a spawned child re-executes
    the main script to resolve them (bootstrap errors without a
    __main__ guard; unresolvable in REPLs/notebooks) — keep fork for
    those. The pickle probe writes to a null sink (no byte copy of
    large in-memory datasets)."""
    import io
    import pickle

    for obj in (dataset, collate_fn, worker_init_fn):
        if obj is None:
            continue
        mod = getattr(type(obj), "__module__", None)
        if callable(obj) and not isinstance(obj, type):
            mod = getattr(obj, "__module__", mod)
        if mod == "__main__":
            return False

    class _Null(io.RawIOBase):
        def write(self, b):
            return len(b)

    try:
        pickle.Pickler(_Null()).dump((dataset, collate_fn, worker_init_fn))
        return True
    except Exception:  # noqa: BLE001 — any pickling failure means fork
        return False


class _child_env:
    """Environment for worker start(): spawned children re-run the
    interpreter, re-importing this package and therefore jax — force the
    CPU backend and drop accelerator-tunnel vars so a DATA worker never
    claims the TPU (single-chip hosts deadlock otherwise)."""

    _SCRUB = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None,
              "PALLAS_AXON_REMOTE_COMPILE": None}

    def __enter__(self):
        import os

        self._saved = {k: os.environ.get(k) for k in self._SCRUB}
        for k, v in self._SCRUB.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def __exit__(self, *exc):
        import os

        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class _MultiprocessIter:
    """Order-preserving fan-out over worker processes.

    Default start method is SPAWN when the dataset/collate/init pickle
    (fresh interpreters — os.fork() under the multithreaded JAX runtime
    can deadlock a child on a lock some backend thread held at fork
    time), falling back to fork with a warning for closure-captured
    datasets. Keeps at most `prefetch` index-batches outstanding per
    worker; results arrive in completion order and are buffered until
    their turn, so the output sequence is identical to single-process
    iteration.
    """

    def __init__(self, dataset, batches, collate_fn, num_workers,
                 worker_init_fn, timeout, prefetch=2, mp_context=None):
        import multiprocessing as mp

        if mp_context is None:
            if _spawn_safe(dataset, collate_fn, worker_init_fn):
                mp_context = "spawn"
            else:
                msg = (
                    "DataLoader: dataset/collate_fn/worker_init_fn are not "
                    "picklable; falling back to fork() workers, which can "
                    "deadlock under the multithreaded JAX runtime — make "
                    "them module-level (picklable) to use spawn"
                )
                from .. import flags as _flags

                if _flags.get_flags(
                        ["FLAGS_dataloader_require_spawn"]
                )["FLAGS_dataloader_require_spawn"]:
                    # production hard-fail (VERDICT r4 weak #4): a silent
                    # fork in a long-running job is a latent deadlock
                    raise RuntimeError(
                        msg + " (raising: FLAGS_dataloader_require_spawn "
                              "is set)")
                import warnings

                warnings.warn(msg, RuntimeWarning, stacklevel=3)
                mp_context = "fork"
        if isinstance(mp_context, str):
            ctx = mp.get_context(mp_context)
        else:
            ctx = mp_context
        self._batches = batches
        self._timeout = timeout if timeout and timeout > 0 else None
        self._index_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._workers = [
            ctx.Process(
                target=_worker_loop,
                args=(dataset, self._index_q, self._result_q, collate_fn,
                      worker_init_fn, w),
                daemon=True,
            )
            for w in range(num_workers)
        ]
        with _child_env():
            for w in self._workers:
                w.start()
        self._send = enumerate(batches)
        self._pending = {}
        self._next = 0
        self._ends_sent = False
        for _ in range(prefetch * num_workers):
            self._submit_one()

    def _submit_one(self):
        nxt = next(self._send, None)
        if nxt is not None:
            self._index_q.put(nxt)
        elif not self._ends_sent:
            for _ in self._workers:
                self._index_q.put(_WORKER_END)
            self._ends_sent = True

    def _get_result(self):
        deadline_each = 1.0
        waited = 0.0
        while True:
            try:
                return self._result_q.get(timeout=deadline_each)
            except _queue.Empty:
                waited += deadline_each
                # a worker that exited nonzero (OOM-kill, segfault) took
                # its in-flight batch with it; waiting on the survivors
                # would deadlock — the batch can never arrive
                crashed = [
                    w for w in self._workers
                    if not w.is_alive() and w.exitcode not in (0, None)
                ]
                if crashed:
                    codes = [w.exitcode for w in crashed]
                    raise RuntimeError(
                        f"DataLoader: {len(crashed)} worker(s) died with "
                        f"exit code(s) {codes} (OOM-killed or crashed?)"
                    ) from None
                if not any(w.is_alive() for w in self._workers):
                    raise RuntimeError(
                        "DataLoader: all workers exited without delivering "
                        "a batch (check worker stderr)"
                    ) from None
                if self._timeout is not None and waited >= self._timeout:
                    raise RuntimeError(
                        f"DataLoader: timed out after {waited:.0f}s waiting "
                        f"for a worker batch"
                    ) from None

    def __iter__(self):
        # prefetch-depth gauge (ISSUE 15): the reorder buffer holds the
        # batches workers finished ahead of the consumer — 0 at a get
        # means the consumer is starved by the worker pool
        from ..reader import _queue_gauge

        depth = _queue_gauge("mp")
        try:
            while self._next < len(self._batches):
                if depth is not None:
                    depth.set(len(self._pending))
                while self._next not in self._pending:
                    tag, payload = self._get_result()
                    if tag == "error":
                        raise RuntimeError(f"DataLoader worker failed:\n{payload}")
                    self._pending[tag] = payload
                    self._submit_one()
                yield self._pending.pop(self._next)
                self._next += 1
        finally:
            self.shutdown()

    def shutdown(self):
        for w in self._workers:
            if w.is_alive():
                w.terminate()
        for w in self._workers:
            w.join(timeout=5)
        for q in (self._index_q, self._result_q):
            q.cancel_join_thread()
            q.close()
