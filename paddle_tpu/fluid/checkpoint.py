"""Preemption-safe checkpointing: atomic, manifest-verified, resumable.

Parity surface: the reference's answer to trainer preemption is
`fluid/io.py` save/load plus a manual restart — a SIGTERM between
`Model.save` calls loses everything. This module is the Orbax-style
robustness layer (cf. the checkpoint/restore discipline of the GPipe and
pathways-style training systems in PAPERS.md): step-numbered checkpoint
directories committed atomically, verified by checksum on load, with
automatic fallback to the newest *valid* checkpoint when the latest was
torn by a crash.

Commit protocol (CheckpointManager.save):

  1. all content files (scope persistables, RNG state, reader position,
     PS-table snapshots) are written into `<root>/.tmp-ckpt-<step>-<pid>`
  2. the tmp dir is renamed to `<root>/ckpt-<step>` — visible but NOT
     yet a checkpoint: a directory without a manifest is torn by
     definition and every reader skips it
  3. `manifest.json` (step + sha256/size of every content file) is
     written via tmp + `os.replace` INTO the step dir — THE commit
     point. A kill anywhere before 3 leaves the previous checkpoint as
     the newest valid one; a kill during 3 leaves either no manifest or
     the complete manifest, never a torn one.

`distributed/faults.py` crash rules (`crash:ckpt_tmp_written:1`,
`crash:ckpt_before_commit:1`) kill the process deterministically between
these phases so tests/test_checkpoint.py PROVES torn-checkpoint recovery
instead of hoping for it.

What a checkpoint holds: every persistable of the program (parameters,
optimizer moments, LR, AMP loss-scale state — all scope-resident), the
scope's RNG key (so dropout streams continue bit-identically), the
caller's `extra_state` (epoch / step / reader position / loss history:
what `Model.fit(resume=...)` and `Executor.train_from_dataset` need for
an exact loss-trace continuation), and the PS tables the program
references (same `<table>.pkl` state_dict format as
`fleet.init_server(model_dir)` / ps_server snapshots), tagged with the
trainer group's generation.

One writer per root directory: multi-trainer jobs checkpoint to
per-rank roots (or rank 0 only) — concurrent writers to one root race
on retention, not on the commit itself.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import signal
import threading
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import framework
from .executor import global_scope
from .io import (_atomic_write_bytes, _persistable_names, _ps_table_names,
                 _save_ps_tables)

MANIFEST = "manifest.json"
MANIFEST_FORMAT = 1
_DIR_RE = re.compile(r"^ckpt-(\d+)$")

# sysexits EX_TEMPFAIL: the conventional "retry me" code — a preempted
# trainer exits with it after its final checkpoint, and the launcher's
# elastic restart respawns a trainer that auto-resumes
PREEMPTED_EXIT_CODE = 75


class BadStepError(FloatingPointError):
    """FLAGS_check_numerics tripped: the step produced non-finite
    gradients (or, for programs without the in-graph guard, non-finite
    updated state). The Executor raises this BEFORE committing anything
    to the scope, so the caller can skip the step — parameters,
    optimizer state and the RNG key are exactly as before the step."""


class Preempted(RuntimeError):
    """Raised by a training loop after it honored a preemption request
    (SIGTERM) with a final checkpoint. Catch it and
    `sys.exit(PREEMPTED_EXIT_CODE)` so the supervisor respawns you."""


class WorldSizeMismatchError(RuntimeError):
    """The checkpoint was written by a job at a different world size
    and elastic re-shard is disabled: resuming it blind would silently
    misalign every rank's data shard. Re-split the data positions
    across the new dp group and restore(allow_reshard=True), or set
    PADDLE_ELASTIC_RESHARD=1 (the launcher's elastic-resize restarts
    do)."""


def _reshard_allowed_from_env() -> bool:
    return os.environ.get("PADDLE_ELASTIC_RESHARD", "").lower() in (
        "1", "true", "yes", "on")


def _world_size_from_env() -> Optional[int]:
    raw = os.environ.get("PADDLE_TRAINERS_NUM")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# preemption signal plumbing
# ---------------------------------------------------------------------------

_preempt_event = threading.Event()
_handler_installed = False
_handler_lock = threading.Lock()


def preemption_requested() -> bool:
    return _preempt_event.is_set()


def request_preemption() -> None:
    """Arm the preemption flag directly (tests: deterministic 'SIGTERM at
    step K' without signal-delivery timing)."""
    _preempt_event.set()


def clear_preemption() -> None:
    _preempt_event.clear()


def install_preemption_handler(signum: int = signal.SIGTERM) -> bool:
    """SIGTERM -> set the preemption flag; training loops drain it at the
    next step boundary (save a final checkpoint, raise Preempted). Chains
    any previously installed handler. Idempotent; returns False when not
    on the main thread (signal.signal would raise there) — the flag can
    still be armed via request_preemption()."""
    global _handler_installed
    with _handler_lock:
        if _handler_installed:
            return True
        try:
            prev = signal.getsignal(signum)

            def _handler(sig, frame):
                _preempt_event.set()
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(sig, frame)

            signal.signal(signum, _handler)
        except ValueError:  # not the main thread
            return False
        _handler_installed = True
        return True


# ---------------------------------------------------------------------------
# RNG state capture (typed rbg keys on TPU, raw PRNGKey arrays on CPU)
# ---------------------------------------------------------------------------


def _rng_state(key) -> Optional[dict]:
    if key is None:
        return None
    import jax
    import jax.numpy as jnp

    try:
        typed = jnp.issubdtype(key.dtype, jax.dtypes.prng_key)
    except (TypeError, AttributeError):
        typed = False
    if not typed:
        return {"typed": False, "data": np.asarray(key)}
    impl = "rbg" if "rbg" in repr(jax.random.key_impl(key)).lower() \
        else "threefry2x32"
    return {"typed": True, "impl": impl,
            "data": np.asarray(jax.random.key_data(key))}


def _restore_rng(state: Optional[dict]):
    if state is None:
        return None
    import jax
    import jax.numpy as jnp

    if not state["typed"]:
        return jnp.asarray(state["data"])
    return jax.random.wrap_key_data(jnp.asarray(state["data"]),
                                    impl=state["impl"])


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _crash_point(phase: str) -> None:
    """Deterministic kill site for torn-checkpoint drills (flag-gated
    no-op in production: one flag read when off)."""
    from ..distributed import faults

    faults.crash_point(phase)


class CheckpointManager:
    """Step-numbered atomic checkpoints with retention and verified,
    fall-back-to-newest-valid restore.

    program/scope given at construction are the defaults for save() and
    restore(); both can be overridden per call. With program=None the
    whole scope is checkpointed (and PS tables are skipped)."""

    def __init__(self, root: str, keep_last_n: int = 3, program=None,
                 scope=None, world_size: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.keep_last_n = max(1, int(keep_last_n))
        self.program = program
        self.scope = scope
        # elastic contract: manifests record the dp world size that
        # wrote them (default: the launcher env); restore refuses a
        # mismatch unless the caller opted into re-sharding
        self.world_size = (int(world_size) if world_size is not None
                           else _world_size_from_env())
        os.makedirs(self.root, exist_ok=True)

    # -- layout ----------------------------------------------------------
    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"ckpt-{int(step):08d}")

    def _scan(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.root):
            m = _DIR_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.root, name)))
        return sorted(out)

    def manifest(self, step: int) -> Optional[dict]:
        """Parsed manifest of a COMMITTED checkpoint, else None (missing
        or unparseable manifest == torn == not a checkpoint)."""
        try:
            with open(os.path.join(self._dir(step), MANIFEST)) as f:
                m = json.load(f)
            return m if m.get("format") == MANIFEST_FORMAT else None
        except (OSError, ValueError):
            return None

    def steps(self) -> List[int]:
        """Steps with a committed manifest, ascending (cheap check: the
        manifest's presence is the commit; verify() adds checksums)."""
        return [s for s, _ in self._scan() if self.manifest(s) is not None]

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def verify(self, step: int) -> bool:
        """Full integrity check: manifest present and every listed file
        exists with matching size and sha256."""
        m = self.manifest(step)
        if m is None:
            return False
        d = self._dir(step)
        for rel, meta in m["files"].items():
            p = os.path.join(d, rel)
            try:
                if os.path.getsize(p) != meta["bytes"]:
                    return False
                if _sha256(p) != meta["sha256"]:
                    return False
            except OSError:
                return False
        return True

    # -- save ------------------------------------------------------------
    def save(self, step: int, extra_state: Optional[dict] = None,
             program=None, scope=None) -> str:
        import time as _time

        from . import monitor
        from ..telemetry import tracing

        t0 = _time.perf_counter()
        # the save span joins the LAST step's trace (saves run between
        # steps, after the step span closed) so tracetop shows the
        # checkpoint hop on the same causal timeline; no-op tracing-off
        with tracing.span("checkpoint_save",
                          parent=tracing.last_step_ctx(),
                          attrs={"step": int(step)}):
            out = self._save_impl(step, extra_state, program, scope)
        # telemetry: checkpoint time is part of the step-time story
        # (attached to the next committed step record + its histogram)
        monitor.observe_checkpoint_save((_time.perf_counter() - t0) * 1e3)
        return out

    def _save_impl(self, step: int, extra_state: Optional[dict] = None,
                   program=None, scope=None) -> str:
        program = program if program is not None else self.program
        scope = scope if scope is not None else (self.scope or global_scope())

        if program is not None:
            names = [n for n in _persistable_names(program)
                     if scope.find_var(n) is not None]
        else:
            names = [n for n, v in scope.vars.items() if v is not None]
        arrays = {n: np.asarray(scope.find_var(n)) for n in names}

        tmp = os.path.join(self.root, f".tmp-ckpt-{int(step):08d}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            _atomic_write_bytes(
                os.path.join(tmp, "state.pkl"),
                pickle.dumps({"arrays": arrays},
                             protocol=pickle.HIGHEST_PROTOCOL))
            _atomic_write_bytes(
                os.path.join(tmp, "rng.pkl"),
                pickle.dumps(_rng_state(scope._rng_key),
                             protocol=pickle.HIGHEST_PROTOCOL))
            _atomic_write_bytes(
                os.path.join(tmp, "extra.pkl"),
                pickle.dumps(dict(extra_state or {}),
                             protocol=pickle.HIGHEST_PROTOCOL))
            ps_tables: List[str] = []
            if program is not None and _ps_table_names(program):
                _save_ps_tables(tmp, program)
                ps_tables = [f[:-4] for f in os.listdir(tmp)
                             if f.endswith(".pkl")
                             and f not in ("state.pkl", "rng.pkl",
                                           "extra.pkl")]
            _crash_point("ckpt_tmp_written")

            final = self._dir(step)
            if os.path.exists(final):  # stale same-step dir (torn or old)
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._fsync_dir(self.root)
        _crash_point("ckpt_before_commit")

        files = {}
        for rel in sorted(os.listdir(final)):
            p = os.path.join(final, rel)
            files[rel] = {"sha256": _sha256(p),
                          "bytes": os.path.getsize(p)}
        manifest = {
            "format": MANIFEST_FORMAT,
            "step": int(step),
            "files": files,
            "ps": {
                "tables": sorted(ps_tables),
                "generation": int(
                    os.environ.get("PADDLE_ELASTIC_RESTART", "0") or 0),
            },
        }
        if self.world_size is not None:
            manifest["world_size"] = int(self.world_size)
            manifest["membership_epoch"] = int(
                os.environ.get("PADDLE_MEMBERSHIP_EPOCH", "0") or 0)
        # THE commit point: tmp + os.replace makes the manifest appear
        # atomically; before this line the directory reads as torn
        _atomic_write_bytes(os.path.join(final, MANIFEST),
                            json.dumps(manifest, indent=1).encode())
        self._fsync_dir(final)
        self._retain()
        return final

    @staticmethod
    def _fsync_dir(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:  # platforms without dir fsync
            pass

    def _retain(self) -> None:
        """Keep the newest keep_last_n COMMITTED checkpoints; everything
        (torn dirs and stale tmp dirs included) older than the oldest
        kept one is garbage. Torn dirs NEWER than the oldest kept
        checkpoint are left alone — restore() skips them anyway and the
        next save at that step overwrites them."""
        valid = self.steps()
        if not valid:
            return
        kept = valid[-self.keep_last_n:]
        cutoff = kept[0]
        for s, path in self._scan():
            if s < cutoff and s not in kept:
                shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.root):
            if name.startswith(".tmp-ckpt-"):
                m = re.match(r"^\.tmp-ckpt-(\d+)-(\d+)$", name)
                if m and (int(m.group(1)) < cutoff
                          or int(m.group(2)) != os.getpid()):
                    shutil.rmtree(os.path.join(self.root, name),
                                  ignore_errors=True)

    # -- restore ---------------------------------------------------------
    def restore(self, step: Optional[int] = None, program=None,
                scope=None, allow_reshard: Optional[bool] = None,
                ) -> Optional[dict]:
        """Restore the given step, or the newest checkpoint that passes
        full verification — a torn or corrupted newer directory is
        skipped with a warning, never trusted. Returns
        {"step", "extra", "manifest", "world_size"} or None when no
        valid checkpoint exists. On success the scope holds the
        checkpointed persistables and RNG key, and any PS tables the
        program references are rolled back to their checkpointed state.

        Elastic gate: a manifest written at a DIFFERENT world size is
        refused (WorldSizeMismatchError — never a silent fallback, the
        older checkpoints have the same world size) unless
        `allow_reshard` (default: PADDLE_ELASTIC_RESHARD env) is true;
        then the caller owns re-splitting its data positions across the
        new dp group and the returned "world_size" says what to re-split
        FROM. Pre-elastic manifests carry no world size and skip the
        check."""
        program = program if program is not None else self.program
        scope = scope if scope is not None else (self.scope or global_scope())
        if allow_reshard is None:
            allow_reshard = _reshard_allowed_from_env()
        candidates = [step] if step is not None else \
            list(reversed(self.steps()))
        for s in candidates:
            if not self.verify(s):
                warnings.warn(
                    f"checkpoint ckpt-{s:08d} at {self.root!r} failed "
                    f"verification (torn write or corruption); falling "
                    f"back to the previous checkpoint",
                    RuntimeWarning, stacklevel=2)
                continue
            m = self.manifest(s)
            ckpt_ws = (m or {}).get("world_size")
            if (ckpt_ws is not None and self.world_size is not None
                    and int(ckpt_ws) != int(self.world_size)
                    and not allow_reshard):
                raise WorldSizeMismatchError(
                    f"checkpoint ckpt-{s:08d} was written by a world of "
                    f"{ckpt_ws} trainers but this job runs "
                    f"{self.world_size}; elastic re-shard is disabled — "
                    f"re-split the data positions and pass "
                    f"allow_reshard=True (or PADDLE_ELASTIC_RESHARD=1)")
            try:
                out = self._load(s, program, scope)
                out["world_size"] = ckpt_ws
                return out
            except Exception as e:  # corrupt despite checksums: skip it
                warnings.warn(
                    f"checkpoint ckpt-{s:08d} failed to load ({e}); "
                    f"falling back", RuntimeWarning, stacklevel=2)
        return None

    def _load(self, step: int, program, scope) -> dict:
        import jax.numpy as jnp

        d = self._dir(step)
        with open(os.path.join(d, "state.pkl"), "rb") as f:
            state = pickle.load(f)
        with open(os.path.join(d, "rng.pkl"), "rb") as f:
            rng = pickle.load(f)
        with open(os.path.join(d, "extra.pkl"), "rb") as f:
            extra = pickle.load(f)
        manifest = self.manifest(step)

        for n, a in state["arrays"].items():
            scope.set_var(n, jnp.asarray(a))
        scope._rng_key = _restore_rng(rng)

        for name in (manifest or {}).get("ps", {}).get("tables", ()):
            path = os.path.join(d, f"{name}.pkl")
            if not os.path.exists(path):
                raise RuntimeError(
                    f"manifest lists PS table {name!r} but {name}.pkl is "
                    f"missing")
            from ..distributed import ps

            try:
                table = ps.get_table(name)
            except KeyError:
                warnings.warn(
                    f"checkpoint holds PS table {name!r} but no such "
                    f"table is registered in this process; create it "
                    f"before restore to roll it back", RuntimeWarning,
                    stacklevel=3)
                continue
            with open(path, "rb") as f:
                table.load_state_dict(pickle.load(f))
        return {"step": int(step), "extra": extra, "manifest": manifest}
