"""Preemption-safe checkpointing: atomic, manifest-verified, resumable,
asynchronous and sharded.

Parity surface: the reference's answer to trainer preemption is
`fluid/io.py` save/load plus a manual restart — a SIGTERM between
`Model.save` calls loses everything. This module is the Orbax-style
robustness layer (cf. the checkpoint/restore discipline of the GPipe and
pathways-style training systems in PAPERS.md): step-numbered checkpoint
directories committed atomically, verified by checksum on load, with
automatic fallback to the newest *valid* checkpoint when the latest was
torn by a crash.

Commit protocol (CheckpointManager.save, single-writer layout):

  1. all content files (scope persistables, RNG state, reader position,
     PS-table snapshots) are written into `<root>/.tmp-ckpt-<step>-<pid>`
     (each fsynced, then the directory — power-loss durability;
     PADDLE_CKPT_FSYNC=0 opts out)
  2. the tmp dir is renamed to `<root>/ckpt-<step>` — visible but NOT
     yet a checkpoint: a directory without a manifest is torn by
     definition and every reader skips it
  3. `manifest.json` (step + sha256/size of every content file) is
     written via tmp + `os.replace` INTO the step dir — THE commit
     point. A kill anywhere before 3 leaves the previous checkpoint as
     the newest valid one; a kill during 3 leaves either no manifest or
     the complete manifest, never a torn one.

Async saves (`PADDLE_CKPT_ASYNC=1` or `save(async_=True)`): the step
loop pays only for the SNAPSHOT — a device→host copy of the scope
persistables, the RNG key, the extra state and the PS-table state dicts,
captured at the step boundary under the same guard semantics as a sync
save — and serialization + sha256 + the two-phase commit run on a
bounded background writer thread. The queue has depth 1 with coalescing:
a new save supersedes a still-queued one (the writer always commits the
NEWEST snapshot it was handed), so the step loop never blocks behind a
slow disk. Writer exceptions latch and re-raise at the next save() /
drain(); SIGTERM-driven final saves go through the synchronous path
(which waits out any in-flight write first) and an atexit hook drains
the queue, so the final checkpoint is never lost.

Sharded jobs (`PADDLE_CKPT_SHARDED=1` with world_size > 1): every rank
writes its own `rank<k>/` shard dir (contents + per-shard manifest,
committed exactly like a single-writer checkpoint) under the SAME
step dir, then reports the shard-manifest sha256 to a commit barrier —
the launcher-hosted `CkptBarrier` over the ps_server RPC transport
(PADDLE_CKPT_BARRIER_ENDPOINT), or a shared-filesystem poll when no
barrier is armed. Rank 0 waits for every rank's report and only then
commits `global_manifest.json` (step, world_size, membership_epoch,
per-shard manifest sha256s) — THE global commit point. `restore()` only
considers steps with a complete global manifest, so a crash between two
ranks' shard commits leaves a checkpoint that is INVISIBLE by
construction (and GC'd as torn once a newer step commits).

`distributed/faults.py` rules drill every phase deterministically:
`crash:<phase>:<nth>` kills at `ckpt_tmp_written`, `ckpt_before_commit`,
`ckpt_manifest_tmp_written` (mid manifest rename), `ckpt_writer` (inside
the async writer thread), `ckpt_shard_committed` (post-shard,
pre-barrier-report) and `ckpt_before_global_commit`; `io_err:<phase>`,
`short_write:<phase>` and `diskfull:<phase>` inject disk faults at the
`ckpt_content`, `ckpt_manifest` and `ckpt_global_manifest` write phases
so tests/test_checkpoint*.py PROVE torn/corrupt-checkpoint recovery
instead of hoping for it. `tools/ckpt_doctor.py` is the offline fsck:
verify manifests + checksums across shards, report and GC torn/corrupt/
orphaned dirs, repair a corrupt PS-table shard from a live replica.

What a checkpoint holds: every persistable of the program (parameters,
optimizer moments, LR, AMP loss-scale state — all scope-resident), the
scope's RNG key (so dropout streams continue bit-identically), the
caller's `extra_state` (epoch / step / reader position / loss history:
what `Model.fit(resume=...)` and `Executor.train_from_dataset` need for
an exact loss-trace continuation), and the PS tables the program
references (same `<table>.pkl` state_dict format as
`fleet.init_server(model_dir)` / ps_server snapshots), tagged with the
trainer group's generation.

One writer per root directory in single-writer mode; in sharded mode
one writer per `rank<k>/` shard and rank 0 owns the global commit and
retention.
"""
from __future__ import annotations

import atexit
import copy
import hashlib
import json
import os
import pickle
import re
import shutil
import signal
import sys
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import framework
from . import io as io_lib
from .executor import global_scope
from .io import _atomic_write_bytes, _persistable_names, _ps_table_names
from ..telemetry import get_registry

_REG = get_registry()

MANIFEST = "manifest.json"
GLOBAL_MANIFEST = "global_manifest.json"
MANIFEST_FORMAT = 1
_DIR_RE = re.compile(r"^ckpt-(\d+)$")
_TMP_RE = re.compile(r"^\.tmp-ckpt-(\d+)-(?:r\d+-)?(\d+)$")

ENV_ASYNC = "PADDLE_CKPT_ASYNC"
ENV_SHARDED = "PADDLE_CKPT_SHARDED"
ENV_BARRIER = "PADDLE_CKPT_BARRIER_ENDPOINT"
ENV_BARRIER_TIMEOUT = "PADDLE_CKPT_BARRIER_TIMEOUT"
ENV_DRAIN_TIMEOUT = "PADDLE_CKPT_DRAIN_TIMEOUT"

# sysexits EX_TEMPFAIL: the conventional "retry me" code — a preempted
# trainer exits with it after its final checkpoint, and the launcher's
# elastic restart respawns a trainer that auto-resumes
PREEMPTED_EXIT_CODE = 75


class BadStepError(FloatingPointError):
    """FLAGS_check_numerics tripped: the step produced non-finite
    gradients (or, for programs without the in-graph guard, non-finite
    updated state). The Executor raises this BEFORE committing anything
    to the scope, so the caller can skip the step — parameters,
    optimizer state and the RNG key are exactly as before the step.

    When the NaN-provenance doctor ran (telemetry/numerics.py, the
    default), `report` carries the provenance dict — the FIRST
    non-finite producer's op index/type, user-layer callstack, operand
    stats and the sampled grad-norm history — and `dump_path` the
    numrec.<tag>.json flight-record it was written to."""

    def __init__(self, message: str, report=None, dump_path=None):
        super().__init__(message)
        self.report = report or {}
        self.dump_path = dump_path


class Preempted(RuntimeError):
    """Raised by a training loop after it honored a preemption request
    (SIGTERM) with a final checkpoint. Catch it and
    `sys.exit(PREEMPTED_EXIT_CODE)` so the supervisor respawns you."""


class WorldSizeMismatchError(RuntimeError):
    """The checkpoint was written by a job at a different world size
    and elastic re-shard is disabled: resuming it blind would silently
    misalign every rank's data shard. Re-split the data positions
    across the new dp group and restore(allow_reshard=True), or set
    PADDLE_ELASTIC_RESHARD=1 (the launcher's elastic-resize restarts
    do)."""


class CheckpointError(RuntimeError):
    """A checkpoint save could not commit (disk fault, barrier
    timeout). The on-disk state is still consistent: restore() falls
    back to the newest fully-committed step."""


class CheckpointWriterError(CheckpointError):
    """A background (async) checkpoint write failed. The error latched
    in the writer and re-raises here — at the save/drain AFTER the
    failure — so the step loop learns about it at the next step
    boundary instead of from a silent gap in the checkpoint chain."""


class RestoreMismatchError(CheckpointError):
    """The checkpoint's arrays disagree with the program's var metadata
    (shape or dtype) — restoring them would fail deep inside the jitted
    step, hundreds of frames from the var that caused it. The message
    names every mismatched var and the layer that created it (scopecheck
    findings), and NOTHING was applied to the scope. restore() does not
    fall back past this: the program changed, not the checkpoint, so
    every older step is equally mismatched."""

    def __init__(self, message: str, findings=()):
        super().__init__(message)
        self.findings = list(findings)


class CommitBarrierError(CheckpointError):
    """Rank 0 gave up waiting for every rank's shard-commit report:
    the step's checkpoint stays torn (no global manifest) and restore()
    keeps serving the previous fully-committed step."""


def _env_true(name: str, default: str = "") -> bool:
    return os.environ.get(name, default).lower() in ("1", "true", "yes",
                                                     "on")


def _reshard_allowed_from_env() -> bool:
    return _env_true("PADDLE_ELASTIC_RESHARD")


def _world_size_from_env() -> Optional[int]:
    raw = os.environ.get("PADDLE_TRAINERS_NUM")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _membership_epoch() -> int:
    try:
        return int(os.environ.get("PADDLE_MEMBERSHIP_EPOCH", "0") or 0)
    except ValueError:
        return 0


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default) or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# preemption signal plumbing
# ---------------------------------------------------------------------------

_preempt_event = threading.Event()
_handler_installed = False
_handler_lock = threading.Lock()


def preemption_requested() -> bool:
    return _preempt_event.is_set()


def request_preemption() -> None:
    """Arm the preemption flag directly (tests: deterministic 'SIGTERM at
    step K' without signal-delivery timing)."""
    _preempt_event.set()


def clear_preemption() -> None:
    _preempt_event.clear()


def install_preemption_handler(signum: int = signal.SIGTERM) -> bool:
    """SIGTERM -> set the preemption flag; training loops drain it at the
    next step boundary (save a final checkpoint, raise Preempted). Chains
    any previously installed handler. Idempotent; returns False when not
    on the main thread (signal.signal would raise there) — the flag can
    still be armed via request_preemption()."""
    global _handler_installed
    with _handler_lock:
        if _handler_installed:
            return True
        try:
            prev = signal.getsignal(signum)

            def _handler(sig, frame):
                _preempt_event.set()
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(sig, frame)

            signal.signal(signum, _handler)
        except ValueError:  # not the main thread
            return False
        _handler_installed = True
        return True


# ---------------------------------------------------------------------------
# RNG state capture (typed rbg keys on TPU, raw PRNGKey arrays on CPU)
# ---------------------------------------------------------------------------


def _rng_state(key) -> Optional[dict]:
    if key is None:
        return None
    import jax
    import jax.numpy as jnp

    try:
        typed = jnp.issubdtype(key.dtype, jax.dtypes.prng_key)
    except (TypeError, AttributeError):
        typed = False
    if not typed:
        return {"typed": False, "data": np.asarray(key)}
    impl = "rbg" if "rbg" in repr(jax.random.key_impl(key)).lower() \
        else "threefry2x32"
    return {"typed": True, "impl": impl,
            "data": np.asarray(jax.random.key_data(key))}


def _restore_rng(state: Optional[dict]):
    if state is None:
        return None
    import jax
    import jax.numpy as jnp

    if not state["typed"]:
        return jnp.asarray(state["data"])
    return jax.random.wrap_key_data(jnp.asarray(state["data"]),
                                    impl=state["impl"])


# ---------------------------------------------------------------------------
# fault-injection shims (one flag read each when the layer is off)
# ---------------------------------------------------------------------------


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _crash_point(phase: str) -> None:
    """Deterministic kill site for torn-checkpoint drills (flag-gated
    no-op in production: one flag read when off)."""
    from ..distributed import faults

    faults.crash_point(phase)


def _io_point(phase: str) -> bool:
    """Deterministic disk-fault site: may raise OSError (io_err /
    diskfull rules); True = simulate a short write (truncate)."""
    from ..distributed import faults

    return faults.io_point(phase)


def _write_content(path: str, blob: bytes, phase: str = "ckpt_content",
                   ) -> None:
    """One checkpoint content file: fault-injectable, fsynced before the
    directory it lives in is renamed into place (the manifest commit
    must never point at bytes still sitting in a volatile cache)."""
    short = _io_point(phase)
    data = blob[: len(blob) // 2] if short else blob
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        if io_lib._fsync_enabled():
            os.fsync(f.fileno())
    _REG.counter("ckpt_bytes_written_total",
                 help="checkpoint bytes written (content + manifests)"
                 ).inc(len(data))


def _files_meta(blobs: Dict[str, bytes]) -> Dict[str, dict]:
    """Manifest `files` map computed from the INTENDED bytes — a short
    or bit-flipped write on disk then fails verification instead of
    being checksummed into legitimacy."""
    return {rel: {"sha256": hashlib.sha256(blobs[rel]).hexdigest(),
                  "bytes": len(blobs[rel])}
            for rel in sorted(blobs)}


# ---------------------------------------------------------------------------
# snapshot job + bounded async writer
# ---------------------------------------------------------------------------


class _Snapshot:
    """Everything a checkpoint commit needs, captured at the step
    boundary: host copies of the arrays, the RNG state, the caller's
    extra state and the PS tables' state dicts. Hand it to the writer
    and the live scope is free to move on."""

    __slots__ = ("step", "arrays", "rng", "extra", "ps_states",
                 "snap_global_step", "save_ctx", "async_")

    def __init__(self, step: int, arrays: dict, rng, extra: dict,
                 ps_states: dict):
        self.step = int(step)
        self.arrays = arrays
        self.rng = rng
        self.extra = extra
        self.ps_states = ps_states
        self.snap_global_step = 0
        self.save_ctx: Optional[Tuple[str, str]] = None
        self.async_ = False


class _AsyncWriter:
    """Depth-1 coalescing write queue + one daemon writer thread.

    submit() replaces any still-queued snapshot (the newest snapshot
    wins — checkpoints are idempotent restart points, not a log), so
    the step loop can save at any frequency without ever queueing
    behind the disk. A writer exception LATCHES: the next
    save()/drain() on the owning manager re-raises it as
    CheckpointWriterError."""

    def __init__(self, mgr: "CheckpointManager"):
        self.mgr = mgr
        self.cond = threading.Condition()
        self.pending: Optional[_Snapshot] = None
        self.active: Optional[_Snapshot] = None
        self.error: Optional[BaseException] = None
        self.closed = False
        self._thread: Optional[threading.Thread] = None

    def _depth_locked(self) -> None:
        d = ((1 if self.pending is not None else 0)
             + (1 if self.active is not None else 0))
        _REG.gauge("ckpt_queue_depth",
                   help="async checkpoint snapshots queued + in flight"
                   ).set(d)

    def submit(self, job: _Snapshot) -> None:
        with self.cond:
            if self.pending is not None:
                _REG.counter(
                    "ckpt_async_superseded_total",
                    help="queued async snapshots replaced by a newer "
                         "save before the writer picked them up").inc()
            self.pending = job
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="paddle-tpu-ckpt-writer")
                self._thread.start()
            self._depth_locked()
            self.cond.notify_all()

    def _loop(self) -> None:
        while True:
            with self.cond:
                while self.pending is None and not self.closed:
                    self.cond.wait()
                if self.pending is None:
                    return
                job, self.pending = self.pending, None
                self.active = job
                self._depth_locked()
            try:
                _crash_point("ckpt_writer")
                self.mgr._write_snapshot(job)
            except BaseException as e:  # noqa: BLE001 — latch + surface
                with self.cond:
                    if self.error is None:
                        self.error = e
                _REG.counter("ckpt_writer_errors_total",
                             help="async checkpoint writes that failed"
                             ).inc()
                try:
                    from ..telemetry import tracing

                    tracing.flight_dump("ckpt_writer_error")
                except Exception:  # noqa: BLE001
                    pass
            finally:
                with self.cond:
                    self.active = None
                    self._depth_locked()
                    self.cond.notify_all()

    def cancel_pending(self) -> None:
        """Drop a still-queued snapshot (a synchronous save is about to
        write something at least as new)."""
        with self.cond:
            if self.pending is not None:
                _REG.counter("ckpt_async_superseded_total").inc()
                self.pending = None
                self._depth_locked()

    def wait_idle(self, timeout: float) -> bool:
        deadline = time.monotonic() + max(0.0, float(timeout))
        with self.cond:
            while self.pending is not None or self.active is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.cond.wait(min(left, 0.5))
        return True

    def take_error(self) -> Optional[BaseException]:
        with self.cond:
            err, self.error = self.error, None
        return err


# ---------------------------------------------------------------------------
# commit-barrier handles (sharded global commit)
# ---------------------------------------------------------------------------


class _LocalBarrier:
    """Direct in-process handle on a coordinator.CkptBarrier (tests,
    and the launcher process itself)."""

    def __init__(self, barrier):
        self.barrier = barrier

    def shard_commit(self, step, rank, world, info) -> None:
        self.barrier.shard_commit(step=int(step), rank=int(rank),
                                  world_size=int(world), info=info)

    def wait_full(self, step, world, timeout) -> Optional[dict]:
        out = self.barrier.wait_full(step=int(step),
                                     world_size=int(world),
                                     timeout=float(timeout))
        if not out.get("complete"):
            return None
        return {int(r): dict(i) for r, i in out["shards"].items()}


class _RPCBarrier:
    """Commit barrier over the ps_server RPC transport (the launcher
    hosts coordinator.CkptBarrier and exports
    PADDLE_CKPT_BARRIER_ENDPOINT). Rank 0 POLLS ckpt_status instead of
    holding a handler thread in a long blocking wait.

    The endpoint may be a comma-separated ordered list (durable
    coordinator + warm standby): verbs rotate to the next endpoint on
    transport failure AND on a ``{"standby": True}`` refusal — an
    unpromoted standby or a stale-latched deposed primary must never
    swallow a commit report."""

    def __init__(self, endpoint: str):
        self.endpoints = [e.strip() for e in str(endpoint).split(",")
                          if e.strip()]
        self.endpoint = self.endpoints[0]
        self._idx = 0
        self._conn = None

    def _c(self):
        if self._conn is None:
            from ..distributed.ps_server import _Conn

            self._conn = _Conn(self.endpoints[self._idx], deadline=10.0,
                               io_timeout=30.0)
        return self._conn

    def _rotate(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:  # noqa: BLE001 — best-effort close
                pass
        self._conn = None
        self._idx = (self._idx + 1) % len(self.endpoints)
        self.endpoint = self.endpoints[self._idx]

    def _call(self, verb: str, **kw) -> dict:
        last: Optional[BaseException] = None
        for _ in range(max(2, len(self.endpoints) * 2)):
            try:
                out = self._c().call(verb, **kw)
            except ConnectionError as e:
                last = e
                self._rotate()
                time.sleep(0.05)
                continue
            if isinstance(out, dict) and out.get("standby"):
                last = ConnectionError(
                    f"barrier endpoint {self.endpoint} is not the "
                    f"authoritative coordinator")
                self._rotate()
                time.sleep(0.05)
                continue
            return out
        raise last if last is not None else ConnectionError(
            "ckpt barrier unreachable")

    def shard_commit(self, step, rank, world, info) -> None:
        self._call("ckpt_shard_commit", step=int(step), rank=int(rank),
                   world_size=int(world), info=info)

    def wait_full(self, step, world, timeout) -> Optional[dict]:
        deadline = time.monotonic() + float(timeout)
        while True:
            try:
                out = self._call("ckpt_status", step=int(step))
            except ConnectionError:
                if time.monotonic() > deadline:
                    return None
                time.sleep(0.2)
                continue
            shards = {int(r): dict(i)
                      for r, i in (out.get("shards") or {}).items()}
            if len(shards) >= int(world):
                return shards
            if time.monotonic() > deadline:
                return None
            time.sleep(0.1)


class _FSBarrier:
    """Shared-filesystem fallback when no barrier endpoint is armed: a
    landed, parseable shard manifest IS the rank's commit report; rank 0
    polls for every rank's and derives the manifest sha256s itself."""

    def __init__(self, mgr: "CheckpointManager"):
        self.mgr = mgr

    def shard_commit(self, step, rank, world, info) -> None:
        pass  # the shard manifest on the shared FS is the report

    def wait_full(self, step, world, timeout) -> Optional[dict]:
        deadline = time.monotonic() + float(timeout)
        stepdir = self.mgr._dir(step)
        while True:
            shards: Optional[dict] = {}
            for r in range(int(world)):
                p = os.path.join(stepdir, f"rank{r}", MANIFEST)
                try:
                    with open(p, "rb") as f:
                        blob = f.read()
                    m = json.loads(blob.decode())
                    if m.get("format") != MANIFEST_FORMAT:
                        raise ValueError("format")
                except (OSError, ValueError):
                    shards = None
                    break
                shards[r] = {
                    "manifest_sha256": hashlib.sha256(blob).hexdigest()}
            if shards is not None:
                return shards
            if time.monotonic() > deadline:
                return None
            time.sleep(0.05)


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------


class CheckpointManager:
    """Step-numbered atomic checkpoints with retention and verified,
    fall-back-to-newest-valid restore; optional async background writes
    and sharded multi-rank layouts with a single global commit point.

    program/scope given at construction are the defaults for save() and
    restore(); both can be overridden per call. With program=None the
    whole scope is checkpointed (and PS tables are skipped).

    async_save (default: PADDLE_CKPT_ASYNC) hands serialization + the
    two-phase commit to a background writer; sharded (default:
    PADDLE_CKPT_SHARDED, only with world_size > 1) writes `rank<k>/`
    shard dirs and gates restore on rank 0's global_manifest.json.
    `barrier` injects an in-process coordinator.CkptBarrier (tests);
    production ranks reach the launcher's over
    PADDLE_CKPT_BARRIER_ENDPOINT, falling back to shared-FS polling."""

    def __init__(self, root: str, keep_last_n: int = 3, program=None,
                 scope=None, world_size: Optional[int] = None,
                 rank: Optional[int] = None,
                 sharded: Optional[bool] = None,
                 async_save: Optional[bool] = None,
                 barrier=None):
        self.root = os.path.abspath(root)
        self.keep_last_n = max(1, int(keep_last_n))
        self.program = program
        self.scope = scope
        # elastic contract: manifests record the dp world size that
        # wrote them (default: the launcher env); restore refuses a
        # mismatch unless the caller opted into re-sharding
        self.world_size = (int(world_size) if world_size is not None
                           else _world_size_from_env())
        self.rank = (int(rank) if rank is not None
                     else int(os.environ.get("PADDLE_TRAINER_ID", "0")
                              or 0))
        if sharded is None:
            sharded = _env_true(ENV_SHARDED) and (self.world_size or 1) > 1
        self.sharded = bool(sharded)
        if async_save is None:
            async_save = _env_true(ENV_ASYNC)
        self.async_save = bool(async_save)
        self.barrier = barrier
        self._bar_handle = None
        self._async: Optional[_AsyncWriter] = None
        os.makedirs(self.root, exist_ok=True)

    # -- layout ----------------------------------------------------------
    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"ckpt-{int(step):08d}")

    def _data_dir(self, step: int) -> str:
        """Where THIS writer's content lives: the step dir itself, or
        this rank's shard dir under it."""
        d = self._dir(step)
        return os.path.join(d, f"rank{self.rank}") if self.sharded else d

    def _scan(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.root):
            m = _DIR_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.root, name)))
        return sorted(out)

    def manifest(self, step: int) -> Optional[dict]:
        """Parsed manifest of a COMMITTED checkpoint — this rank's shard
        manifest in sharded mode — else None (missing or unparseable
        manifest == torn == not a checkpoint)."""
        try:
            with open(os.path.join(self._data_dir(step), MANIFEST)) as f:
                m = json.load(f)
            return m if m.get("format") == MANIFEST_FORMAT else None
        except (OSError, ValueError):
            return None

    def global_manifest(self, step: int) -> Optional[dict]:
        """Parsed global manifest of a sharded checkpoint (None = torn,
        absent, or a non-sharded layout)."""
        try:
            with open(os.path.join(self._dir(step), GLOBAL_MANIFEST)) as f:
                m = json.load(f)
            return m if m.get("format") == MANIFEST_FORMAT else None
        except (OSError, ValueError):
            return None

    def steps(self) -> List[int]:
        """COMMITTED steps, ascending. The commit marker is the manifest
        — the GLOBAL manifest for sharded layouts, so a step some ranks
        finished and others did not is not a checkpoint at all."""
        if self.sharded:
            return [s for s, _ in self._scan()
                    if self.global_manifest(s) is not None]
        return [s for s, _ in self._scan() if self.manifest(s) is not None]

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    @staticmethod
    def _verify_files(d: str, files: Dict[str, dict]) -> bool:
        for rel, meta in files.items():
            p = os.path.join(d, rel)
            try:
                if os.path.getsize(p) != meta["bytes"]:
                    return False
                if _sha256(p) != meta["sha256"]:
                    return False
            except OSError:
                return False
        return True

    def verify(self, step: int) -> bool:
        """Full integrity check: manifest present and every listed file
        exists with matching size and sha256. Sharded: the global
        manifest must list world_size shards whose manifest files hash
        to the recorded sha256s, and THIS rank's shard contents are
        checksummed in full (tools/ckpt_doctor.py cross-checks every
        shard's contents offline)."""
        if self.sharded:
            gm = self.global_manifest(step)
            if gm is None:
                return False
            shards = gm.get("shards") or {}
            if len(shards) != int(gm.get("world_size") or 0):
                return False
            d = self._dir(step)
            for rname, info in shards.items():
                p = os.path.join(d, rname, MANIFEST)
                try:
                    with open(p, "rb") as f:
                        blob = f.read()
                except OSError:
                    return False
                if hashlib.sha256(blob).hexdigest() != \
                        info.get("manifest_sha256"):
                    return False
        m = self.manifest(step)
        if m is None:
            return False
        return self._verify_files(self._data_dir(step), m["files"])

    # -- async plumbing --------------------------------------------------
    def _writer(self) -> _AsyncWriter:
        if self._async is None:
            self._async = _AsyncWriter(self)
            # drain on interpreter exit: the last async save must land
            # even when the caller never reaches a drain point
            atexit.register(self._atexit_drain)
        return self._async

    def _drain_timeout(self) -> float:
        return _float_env(ENV_DRAIN_TIMEOUT, 120.0)

    def _barrier_timeout(self) -> float:
        return _float_env(ENV_BARRIER_TIMEOUT, 120.0)

    def raise_if_async_failed(self) -> None:
        """Surface a latched background-writer failure (no-op when the
        writer never ran or never failed). Training loops call this at
        the step boundary; save() and drain() call it themselves."""
        w = self._async
        if w is None:
            return
        err = w.take_error()
        if err is not None:
            raise CheckpointWriterError(
                f"async checkpoint write failed: "
                f"{type(err).__name__}: {err}") from err

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every queued/in-flight async save is durably
        committed; re-raises a latched writer error. The preemption and
        atexit paths run through here so the final checkpoint is never
        lost."""
        w = self._async
        if w is not None:
            if not w.wait_idle(timeout if timeout is not None
                               else self._drain_timeout()):
                raise CheckpointError(
                    "timed out draining the async checkpoint writer")
        self.raise_if_async_failed()

    def _atexit_drain(self) -> None:
        w = self._async
        if w is None:
            return
        w.wait_idle(self._drain_timeout())
        err = w.take_error()
        if err is not None:  # exiting: report, don't raise
            print(f"[checkpoint] async writer failed at exit: "
                  f"{type(err).__name__}: {err}", file=sys.stderr)

    def _barrier_handle(self):
        if self._bar_handle is None:
            if self.barrier is not None:
                self._bar_handle = _LocalBarrier(self.barrier)
            elif os.environ.get(ENV_BARRIER):
                self._bar_handle = _RPCBarrier(os.environ[ENV_BARRIER])
            else:
                self._bar_handle = _FSBarrier(self)
        return self._bar_handle

    # -- save ------------------------------------------------------------
    def save(self, step: int, extra_state: Optional[dict] = None,
             program=None, scope=None,
             async_: Optional[bool] = None) -> str:
        """Checkpoint `step`. async_ None defaults to the manager's
        async_save (PADDLE_CKPT_ASYNC); async saves return after the
        SNAPSHOT with the path the writer will commit to. async_=False
        forces a synchronous commit — the preemption/final-save path —
        after superseding any queued snapshot and waiting out an
        in-flight write (two writers never interleave). A latched
        background failure from an earlier async save re-raises HERE,
        before anything new is captured."""
        from . import monitor
        from ..telemetry import tracing

        self.raise_if_async_failed()
        if async_ is None:
            async_ = self.async_save
        t0 = time.perf_counter()
        # the save span joins the LAST step's trace (saves run between
        # steps, after the step span closed) so tracetop shows the
        # checkpoint hop on the same causal timeline; no-op tracing-off
        with tracing.span("checkpoint_save",
                          parent=tracing.last_step_ctx(),
                          attrs={"step": int(step)}) as sp:
            job = self._snapshot(step, extra_state, program, scope,
                                 deep=bool(async_))
            job.async_ = bool(async_)
            if sp is not None:
                job.save_ctx = (sp.trace_id, sp.span_id)
            if async_:
                self._writer().submit(job)
                out = self._data_dir(step)
            else:
                w = self._async
                if w is not None:
                    w.cancel_pending()
                    w.wait_idle(self._drain_timeout())
                out = self._write_snapshot(job)
        # telemetry: the step loop's share of checkpoint time (snapshot
        # only, for async saves) lands on the next committed step record
        monitor.observe_checkpoint_save((time.perf_counter() - t0) * 1e3)
        return out

    def _snapshot(self, step: int, extra_state: Optional[dict],
                  program, scope, deep: bool) -> _Snapshot:
        """Capture a consistent host snapshot at the step boundary:
        device→host copies of the persistables, the RNG state, the extra
        state and the PS tables' state dicts. `deep` (async) decouples
        every buffer from the live scope — the next step may donate or
        overwrite device memory while the writer serializes."""
        from . import monitor

        program = program if program is not None else self.program
        scope = scope if scope is not None else (self.scope or global_scope())

        if program is not None:
            names = [n for n in _persistable_names(program)
                     if scope.find_var(n) is not None]
        else:
            names = [n for n, v in scope.vars.items() if v is not None]
        arrays = {}
        for n in names:
            a = np.asarray(scope.find_var(n))
            arrays[n] = np.array(a, copy=True) if deep else a

        rng = _rng_state(scope._rng_key)
        if deep and rng is not None and isinstance(rng.get("data"),
                                                  np.ndarray):
            rng = dict(rng, data=rng["data"].copy())
        extra = (copy.deepcopy(dict(extra_state or {})) if deep
                 else dict(extra_state or {}))

        ps_states: Dict[str, Any] = {}
        if program is not None:
            from ..distributed import ps

            for name in _ps_table_names(program):
                try:
                    t = ps.get_table(name)
                except KeyError:
                    # surface NOW, not at the far-away restore: loading
                    # this "successful" checkpoint would fail on the
                    # missing .pkl
                    warnings.warn(
                        f"save: program references PS table {name!r} but "
                        f"no such table is registered in this process — "
                        f"the checkpoint will NOT contain it and "
                        f"load_persistables will reject it. create_table "
                        f"before saving (or drop the lookup op)",
                        RuntimeWarning, stacklevel=4)
                    continue
                # state_dict deep-copies under the table locks: the
                # snapshot is consistent even while pushes continue
                ps_states[name] = t.state_dict()

        job = _Snapshot(step, arrays, rng, extra, ps_states)
        job.snap_global_step = monitor.global_step()
        return job

    def _write_snapshot(self, job: _Snapshot) -> str:
        """Serialize + checksum + two-phase commit (runs inline for sync
        saves, on the writer thread for async ones)."""
        from . import monitor
        from ..telemetry import tracing

        t0 = time.perf_counter()
        blobs = {
            "state.pkl": pickle.dumps({"arrays": job.arrays},
                                      protocol=pickle.HIGHEST_PROTOCOL),
            "rng.pkl": pickle.dumps(job.rng,
                                    protocol=pickle.HIGHEST_PROTOCOL),
            "extra.pkl": pickle.dumps(job.extra,
                                      protocol=pickle.HIGHEST_PROTOCOL),
        }
        for name, st in sorted(job.ps_states.items()):
            # default protocol: the exact bytes fleet.init_server /
            # ps_server snapshot preload already reads
            blobs[f"{name}.pkl"] = pickle.dumps(st)
        # the write span parents under the save span that captured the
        # snapshot — /tracez and tracetop show the async write hanging
        # off its step's checkpoint_save even though it runs later, on
        # another thread
        with tracing.child_span("checkpoint_write", job.save_ctx,
                                attrs={"step": job.step,
                                       "mode": ("async" if job.async_
                                                else "sync")}):
            if self.sharded:
                out = self._write_shard(job, blobs)
            else:
                out = self._write_single(job, blobs)
        _REG.histogram("checkpoint_write_ms",
                       help="serialize+commit durations (writer side)"
                       ).observe((time.perf_counter() - t0) * 1e3)
        lag = max(0, monitor.global_step() - job.snap_global_step)
        _REG.gauge("ckpt_save_lag_steps",
                   help="steps the loop advanced while the last "
                        "checkpoint was being written").set(lag)
        _REG.gauge("ckpt_save_lag_steps_peak",
                   help="high-water of ckpt_save_lag_steps").set_max(lag)
        return out

    def _ps_section(self, job: _Snapshot) -> dict:
        return {
            "tables": sorted(job.ps_states),
            "generation": int(
                os.environ.get("PADDLE_ELASTIC_RESTART", "0") or 0),
        }

    def _commit_manifest(self, path: str, manifest: dict, io_phase: str,
                         crash_phase: str = "ckpt_manifest_tmp_written",
                         ) -> str:
        """THE commit point: tmp + os.replace makes the manifest appear
        atomically; before this the directory reads as torn. Returns the
        sha256 of the INTENDED manifest bytes (what the global manifest
        records for a shard)."""
        blob = json.dumps(manifest, indent=1).encode()
        short = _io_point(io_phase)
        data = blob[: len(blob) // 2] if short else blob
        _atomic_write_bytes(path, data, crash_phase=crash_phase)
        _REG.counter("ckpt_bytes_written_total",
                     help="checkpoint bytes written (content + manifests)"
                     ).inc(len(data))
        return hashlib.sha256(blob).hexdigest()

    def _write_single(self, job: _Snapshot, blobs: Dict[str, bytes]) -> str:
        step = job.step
        tmp = os.path.join(self.root,
                           f".tmp-ckpt-{step:08d}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            for rel in sorted(blobs):
                _write_content(os.path.join(tmp, rel), blobs[rel])
            io_lib._fsync_dir(tmp)
            _crash_point("ckpt_tmp_written")

            final = self._dir(step)
            if os.path.exists(final):  # stale same-step dir (torn or old)
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        io_lib._fsync_dir(self.root)
        _crash_point("ckpt_before_commit")

        manifest = {
            "format": MANIFEST_FORMAT,
            "step": step,
            "files": _files_meta(blobs),
            "ps": self._ps_section(job),
        }
        if self.world_size is not None:
            manifest["world_size"] = int(self.world_size)
            manifest["membership_epoch"] = _membership_epoch()
        self._commit_manifest(os.path.join(final, MANIFEST), manifest,
                              "ckpt_manifest")
        self._retain()
        return final

    def _write_shard(self, job: _Snapshot, blobs: Dict[str, bytes]) -> str:
        """Sharded commit: shard contents + shard manifest exactly like
        a single-writer checkpoint, then the commit barrier, then (rank
        0 only) the global manifest — the ONLY marker restore trusts."""
        step = job.step
        stepdir = self._dir(step)
        os.makedirs(stepdir, exist_ok=True)
        tmp = os.path.join(
            self.root, f".tmp-ckpt-{step:08d}-r{self.rank}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            for rel in sorted(blobs):
                _write_content(os.path.join(tmp, rel), blobs[rel])
            io_lib._fsync_dir(tmp)
            _crash_point("ckpt_tmp_written")

            final = os.path.join(stepdir, f"rank{self.rank}")
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        io_lib._fsync_dir(stepdir)
        _crash_point("ckpt_before_commit")

        manifest = {
            "format": MANIFEST_FORMAT,
            "step": step,
            "rank": int(self.rank),
            "files": _files_meta(blobs),
            "ps": self._ps_section(job),
        }
        man_sha = self._commit_manifest(os.path.join(final, MANIFEST),
                                        manifest, "ckpt_manifest")
        # the shard is committed but INVISIBLE: without the global
        # manifest no restore anywhere considers this step
        _crash_point("ckpt_shard_committed")

        world = int(self.world_size or 1)
        barrier = self._barrier_handle()
        barrier.shard_commit(step, int(self.rank), world,
                             {"manifest_sha256": man_sha})
        if int(self.rank) != 0:
            return final

        shards = barrier.wait_full(step, world, self._barrier_timeout())
        if shards is None:
            raise CommitBarrierError(
                f"commit barrier for step {step} incomplete after "
                f"{self._barrier_timeout():.0f}s — the step stays torn "
                f"(no global manifest); restore() keeps serving the "
                f"previous fully-committed step")
        _crash_point("ckpt_before_global_commit")
        gm = {
            "format": MANIFEST_FORMAT,
            "step": step,
            "world_size": world,
            "membership_epoch": _membership_epoch(),
            "shards": {f"rank{r}": dict(info)
                       for r, info in sorted(shards.items())},
        }
        self._commit_manifest(os.path.join(stepdir, GLOBAL_MANIFEST), gm,
                              "ckpt_global_manifest",
                              crash_phase="ckpt_global_manifest_tmp_written")
        self._retain()
        return final

    def _retain(self) -> None:
        """Keep the newest keep_last_n COMMITTED checkpoints. Retention
        counts ONLY committed steps — torn dirs never consume a slot and
        the newest valid checkpoint is never deleted no matter how many
        newer torn dirs exist. Torn dirs BELOW the newest committed step
        can never complete (a newer commit exists) and are GC'd; a torn
        dir at/above it may be a save in flight and is left for the next
        save at that step (or tools/ckpt_doctor.py --gc) to clear. In
        sharded mode rank 0 owns retention."""
        if self.sharded and int(self.rank) != 0:
            return
        valid = self.steps()
        if not valid:
            return
        kept = valid[-self.keep_last_n:]
        cutoff = kept[0]
        newest = valid[-1]
        for s, path in self._scan():
            if s in kept:
                continue
            if s < cutoff:
                shutil.rmtree(path, ignore_errors=True)
            elif s < newest and s not in valid:
                _REG.counter("ckpt_torn_gcd_total",
                             help="torn (never-committed) checkpoint "
                                  "dirs garbage-collected").inc()
                shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.root):
            m = _TMP_RE.match(name)
            if not m:
                continue
            t_step, t_pid = int(m.group(1)), int(m.group(2))
            # another pid's tmp dir at a step NEWER than the newest
            # commit may be a live sibling rank's shard write in flight
            # (sharded ranks share the root); it only becomes provable
            # trash once that step commits — a committed step means
            # every rank renamed its tmp away already
            if t_step < cutoff or (t_pid != os.getpid()
                                   and t_step <= newest):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    # -- restore ---------------------------------------------------------
    def restore(self, step: Optional[int] = None, program=None,
                scope=None, allow_reshard: Optional[bool] = None,
                ) -> Optional[dict]:
        """Restore the given step, or the newest checkpoint that passes
        full verification — a torn or corrupted newer directory is
        skipped with a warning, never trusted. A sharded step without a
        complete global manifest is invisible by construction. Returns
        {"step", "extra", "manifest", "world_size"} or None when no
        valid checkpoint exists. On success the scope holds the
        checkpointed persistables and RNG key, and any PS tables the
        program references are rolled back to their checkpointed state.

        Elastic gate: a manifest written at a DIFFERENT world size is
        refused (WorldSizeMismatchError — never a silent fallback, the
        older checkpoints have the same world size) unless
        `allow_reshard` (default: PADDLE_ELASTIC_RESHARD env) is true;
        then the caller owns re-splitting its data positions across the
        new dp group and the returned "world_size" says what to re-split
        FROM. Pre-elastic manifests carry no world size and skip the
        check."""
        program = program if program is not None else self.program
        scope = scope if scope is not None else (self.scope or global_scope())
        if allow_reshard is None:
            allow_reshard = _reshard_allowed_from_env()
        candidates = [step] if step is not None else \
            list(reversed(self.steps()))
        for s in candidates:
            if not self.verify(s):
                warnings.warn(
                    f"checkpoint ckpt-{s:08d} at {self.root!r} failed "
                    f"verification (torn write or corruption); falling "
                    f"back to the previous checkpoint",
                    RuntimeWarning, stacklevel=2)
                continue
            src = self.global_manifest(s) if self.sharded \
                else self.manifest(s)
            ckpt_ws = (src or {}).get("world_size")
            if (ckpt_ws is not None and self.world_size is not None
                    and int(ckpt_ws) != int(self.world_size)
                    and not allow_reshard):
                raise WorldSizeMismatchError(
                    f"checkpoint ckpt-{s:08d} was written by a world of "
                    f"{ckpt_ws} trainers but this job runs "
                    f"{self.world_size}; elastic re-shard is disabled — "
                    f"re-split the data positions and pass "
                    f"allow_reshard=True (or PADDLE_ELASTIC_RESHARD=1)")
            try:
                t0 = time.perf_counter()
                out = self._load(s, program, scope)
                out["world_size"] = ckpt_ws
                try:
                    # goodput ledger (ISSUE 15): restore windows are
                    # recovery cost, not idle (no-op unless armed)
                    from ..telemetry import goodput as _goodput

                    _goodput.on_restore(
                        (time.perf_counter() - t0) * 1e3)
                except Exception:  # noqa: BLE001 — accounting only
                    pass
                return out
            except RestoreMismatchError:
                # program/checkpoint metadata disagreement: every older
                # checkpoint is equally mismatched (the PROGRAM changed)
                # — falling back would just repeat the error N times
                raise
            except Exception as e:  # corrupt despite checksums: skip it
                warnings.warn(
                    f"checkpoint ckpt-{s:08d} failed to load ({e}); "
                    f"falling back", RuntimeWarning, stacklevel=2)
        return None

    def _load(self, step: int, program, scope) -> dict:
        import jax.numpy as jnp

        d = self._data_dir(step)
        with open(os.path.join(d, "state.pkl"), "rb") as f:
            state = pickle.load(f)
        with open(os.path.join(d, "rng.pkl"), "rb") as f:
            rng = pickle.load(f)
        with open(os.path.join(d, "extra.pkl"), "rb") as f:
            extra = pickle.load(f)
        manifest = self.manifest(step)

        # scope-aware lint BEFORE anything touches the scope: a restored
        # array whose shape/dtype disagrees with the program var would
        # otherwise fail inside jit on the next step. Only the
        # intersection is checked — partial restores (a program that
        # grew a layer since the save) are legitimate and the startup
        # program owns the rest.
        if program is not None:
            from .analysis import ERROR as _AN_ERROR
            from .analysis import verify_scope as _verify_scope

            mismatched = [
                f for f in _verify_scope(program, state["arrays"],
                                         check_orphans=False)
                if f.severity == _AN_ERROR and f.check in
                ("scope-shape-mismatch", "scope-dtype-mismatch")]
            if mismatched:
                raise RestoreMismatchError(
                    f"checkpoint ckpt-{step:08d} disagrees with the "
                    f"program on {len(mismatched)} var(s); nothing was "
                    f"restored:\n" + "\n".join(
                        "  " + f.format() for f in mismatched),
                    findings=mismatched)

        for n, a in state["arrays"].items():
            scope.set_var(n, jnp.asarray(a))
        scope._rng_key = _restore_rng(rng)

        for name in (manifest or {}).get("ps", {}).get("tables", ()):
            path = os.path.join(d, f"{name}.pkl")
            if not os.path.exists(path):
                raise RuntimeError(
                    f"manifest lists PS table {name!r} but {name}.pkl is "
                    f"missing")
            from ..distributed import ps

            try:
                table = ps.get_table(name)
            except KeyError:
                warnings.warn(
                    f"checkpoint holds PS table {name!r} but no such "
                    f"table is registered in this process; create it "
                    f"before restore to roll it back", RuntimeWarning,
                    stacklevel=3)
                continue
            with open(path, "rb") as f:
                table.load_state_dict(pickle.load(f))
        out = {"step": int(step), "extra": extra, "manifest": manifest}
        if self.sharded:
            out["global_manifest"] = self.global_manifest(step)
        return out
