"""Weight-decay regularizers. Parity: python/paddle/fluid/regularizer.py."""
from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.regularization_coeff = float(regularization_coeff)

    def append_regularization_op(self, param, grad, block):
        decayed = block.create_var(
            name=grad.name + "@L2DECAY", dtype=grad.dtype, shape=grad.shape
        )
        block.append_op(
            type="scale",
            inputs={"X": [param]},
            outputs={"Out": [decayed]},
            attrs={"scale": self.regularization_coeff},
        )
        out = block.create_var(
            name=grad.name + "@REG", dtype=grad.dtype, shape=grad.shape
        )
        block.append_op(
            type="sum", inputs={"X": [grad, decayed]}, outputs={"Out": [out]}
        )
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.regularization_coeff = float(regularization_coeff)

    def append_regularization_op(self, param, grad, block):
        sign = block.create_var(
            name=grad.name + "@L1SIGN", dtype=grad.dtype, shape=grad.shape
        )
        block.append_op(type="sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decayed = block.create_var(
            name=grad.name + "@L1DECAY", dtype=grad.dtype, shape=grad.shape
        )
        block.append_op(
            type="scale",
            inputs={"X": [sign]},
            outputs={"Out": [decayed]},
            attrs={"scale": self.regularization_coeff},
        )
        out = block.create_var(
            name=grad.name + "@REG", dtype=grad.dtype, shape=grad.shape
        )
        block.append_op(
            type="sum", inputs={"X": [grad, decayed]}, outputs={"Out": [out]}
        )
        return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None:
            out.append((param, grad))
        else:
            out.append((param, reg.append_regularization_op(param, grad, grad.block)))
    return out
