"""CompiledProgram / BuildStrategy / ExecutionStrategy.

Parity surface: reference python/paddle/fluid/compiler.py
(CompiledProgram:87, with_data_parallel:160) + pybind BuildStrategy /
ExecutionStrategy structs (framework/details/build_strategy.h:37).

TPU-native behavior: with_data_parallel does NOT clone the graph per
device (the reference's ParallelExecutor SSA path) — it attaches a
dp-axis Mesh and batch shardings to the program, and the Executor jits
the whole block over it; XLA SPMD inserts the gradient all-reduces.
BuildStrategy fusion/memory knobs are accepted and documented as
subsumed: XLA performs op fusion and buffer liveness natively.
"""
from __future__ import annotations

from typing import Optional


class BuildStrategy:
    """Accepted reference knobs; on TPU most map to XLA behavior."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = 0
        # subsumed by XLA fusion / liveness — accepted, inert:
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_all_reduce_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1  # XLA owns scheduling
        self.num_iteration_per_drop_scope = 1
        self.allow_op_delay = False


class CompiledProgram:
    """Wraps a Program; the Executor unwraps via the `_program` attr."""

    def __init__(self, program_or_graph, build_strategy: Optional[BuildStrategy] = None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._is_data_parallel = False

    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from=None,
        places=None,
    ):
        """reference compiler.py:160 — here: mesh + sharding attach."""
        from ..parallel import create_mesh, shard_program_data_parallel

        self._build_strategy = build_strategy or self._build_strategy
        self._exec_strategy = exec_strategy
        n = len(places) if places else -1
        mesh = create_mesh({"dp": n})
        shard_program_data_parallel(self._program, mesh, axis="dp")
        self._program._mesh = mesh
        self._is_data_parallel = True
        return self
