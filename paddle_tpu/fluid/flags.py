"""Global flags registry (reference platform/flags.cc:33-485 — 27 gflags
re-exported to Python via global_value_getter_setter.cc and settable with
FLAGS_* environment variables).

TPU-native notes: flags that tuned the CUDA allocator / cuDNN / NCCL are
accepted for API parity but inert — PJRT owns memory and XLA owns
collectives; each such flag documents what subsumes it. Meaningful flags
are wired where listed.
"""
from __future__ import annotations

import os
from typing import Any, Dict

# flag -> (default, wired_into | None)
_DEFS: Dict[str, tuple] = {
    # --- wired ---
    "FLAGS_check_nan_inf": (False, "Executor.run scans fetches + updated "
                                   "state every step and raises naming the "
                                   "first bad variable"),
    "FLAGS_benchmark": (False, "Executor.run blocks until the step "
                               "finishes (sync timing)"),
    "FLAGS_use_flash_attention": (True, "ops/attention.py pallas gate"),
    "FLAGS_use_fused_ln": (True, "ops/pallas/add_ln.py residual+LayerNorm "
                                 "kernel gate (encoder/decoder stacks, "
                                 "layer_norm emitter)"),
    "FLAGS_enable_unused_var_check": (
        False, "Executor._compile warns when a feed variable is consumed "
               "by no op (reference unused_var_check.cc / operator.cc:987 "
               "— the silently-ignored-input bug class)"),
    "FLAGS_conv_bn_fusion": (
        False, "fluid/fusion_pass.py: rewrite conv2d->batch_norm[->relu] "
               "triples into one fused_conv_bn op before append_backward "
               "(Pallas conv+stats+normalize mega-kernel, "
               "ops/pallas/conv_bn.py; is_test folds BN into the conv "
               "weights). Applied by Optimizer.backward and the AMP "
               "decorator; off = program is bit-identical to the unfused "
               "baseline"),
    "FLAGS_pipeline_single_program_fallback": (
        False, "fluid/optimizer.py PipelineOptimizer: explicitly accept "
               "multi-stage device_guard programs as ONE co-scheduled XLA "
               "program (warn instead of raise). Off = minimize raises, "
               "honoring the no-silently-ignored-flags rule: stage tags "
               "name a partition the single-program lowering does not "
               "perform"),
    "FLAGS_conv_dw_im2col": (
        False, "ops/nn_ops.py conv2d: reformulate the WEIGHT gradient as "
               "im2col patches + one matmul (MXU-friendly) instead of "
               "XLA's dW-convolution lowering; NHWC groups=1 non-1x1 "
               "kernels only. The TPU answer to the reference's cudnn "
               "exhaustive dW algo search (conv_cudnn_op.cu.cc)"),
    "FLAGS_ps_fault_injection": (
        False, "distributed/faults.py: deterministic fault layer "
               "(PADDLE_PS_FAULT_SPEC rules drop/refuse/delay the Nth "
               "client RPC, kill the pserver after N handled RPCs, or "
               "crash the process at a named phase of the checkpoint "
               "commit protocol) — drives tests/test_ps_faults.py, "
               "tests/test_checkpoint.py and the tools/ci.sh chaos "
               "smoke. Off = injector() returns None and the data plane "
               "is bit-identical to a build without the layer"),
    "FLAGS_check_numerics": (
        False, "bad-step guard on the fp32 path (AMP has its own "
               "found_inf protocol): Optimizer.apply_gradients emits an "
               "in-graph any-gradient-non-finite reduction into a "
               "persistable check_numerics_bad_* var, Executor.run "
               "refuses to commit a step whose guard tripped (raises "
               "checkpoint.BadStepError with the scope untouched), and "
               "the training loops (Model.fit, train_from_dataset) skip "
               "the step — after FLAGS_check_numerics_max_bad_steps "
               "consecutive bad steps they roll back to the last valid "
               "checkpoint. Off = no guard ops, donation unchanged: "
               "bit-identical to baseline"),
    "FLAGS_check_numerics_max_bad_steps": (
        3, "consecutive BadStepError count that triggers a rollback to "
           "the newest valid checkpoint (or re-raises when no "
           "CheckpointManager is active). Only read when "
           "FLAGS_check_numerics is on"),
    "FLAGS_tensor_stats": (
        False, "in-graph tensor statistics (telemetry/numerics.py): "
               "graph construction (Optimizer.apply_gradients, "
               "fluid/clip.py global-norm clip) appends one "
               "tensor_stats reduction per watched variable — "
               "per-layer gradients, parameters, the clip global norm "
               "— into persistable numstat__* vars that ride the "
               "step's state outputs; the host samples them every "
               "PADDLE_NUMERICS_EVERY steps into kind=\"numerics\" "
               "sink records, numerics_* gauges and the /numericz "
               "history ring (tools/numtop.py is the CLI). The flag "
               "rides the Executor compile-cache key; off = no stat "
               "vars or ops are built and the program, loss trace and "
               "step-record schema are bit-identical to a build "
               "without the layer"),
    "FLAGS_check_numerics_amp_scale_floor": (
        1.0, "unified AMP path for the bad-step guard: with "
             "FLAGS_check_numerics on, an fp16 dynamic-loss-scaling "
             "overflow that would push the scale BELOW this floor "
             "(backoff exhausted — the model is producing non-finite "
             "values at any scale) trips a check_numerics_bad_amp_* "
             "guard var, so the Executor raises BadStepError and the "
             "NaN-provenance doctor dumps a numrec for AMP runs too. "
             "Transient overflows (scale still above the floor) keep "
             "AMP's zero-and-shrink skip semantics. Only read when "
             "FLAGS_check_numerics is on"),
    "FLAGS_program_verify": (
        False, "fluid/analysis static verifier: Executor._ensure_compiled "
               "verifies every program on compile-cache miss (raising "
               "ProgramVerifyError with the offending op's build-time "
               "call stack instead of letting XLA fail later), and "
               "apply_conv_bn_fusion / append_backward run pass-"
               "sandwiched (verify before/after; NEW error findings are "
               "attributed to the pass, MLIR-verifier style). Off = no "
               "check runs and the compile path is bit-identical. "
               "Standalone linting: tools/proglint.py"),
    "FLAGS_op_callstack": (
        True, "Block.append_op captures the Python call stack into the "
              "op's __op_callstack__ attr (reference OpDesc op_callstack) "
              "so verifier findings point at the USER layer call. Capture "
              "is a frame walk (no source reads, ~µs/op); disable for "
              "build-speed-critical jobs — diagnostics then lose source "
              "attribution"),
    "FLAGS_op_profile": (
        False, "per-op device-time attribution (telemetry/cost.py): the "
               "Executor wraps each op's lowering in "
               "jax.named_scope('op<idx>:<type>') so xplane device events "
               "carry the op scope in their HLO op_name metadata — "
               "tools/proftop.py and telemetry.cost join the profile back "
               "to Program IR ops (+ user callstacks). The flag is part "
               "of the compile-cache key; off = the traced computation is "
               "bit-identical to a build without the layer"),
    "FLAGS_mem_profile": (
        False, "per-op HBM attribution (telemetry/memory.py): on every "
               "compile-cache miss the static live-range pass "
               "(fluid/analysis/liverange.py) computes per-variable "
               "byte sizes, first-def/last-use ranges and the peak "
               "simultaneous-bytes estimate, publishes the "
               "hbm_* gauges and the debugz /memz report, and emits a "
               "kind=\"mem_report\" sink record. Host-only analysis — "
               "NOT in the compile-cache key (the traced computation is "
               "unchanged); off = one flag read per compile miss and "
               "step records / wire bytes / loss trace are "
               "bit-identical. The OOM doctor and the "
               "PADDLE_HBM_BUDGET_BYTES gate work independently of "
               "this flag; tools/memtop.py is the CLI"),
    "FLAGS_kernel_autotune": (
        False, "Pallas kernel autotuner (paddle_tpu/tuning): the three "
               "Pallas kernels (flash attention BSH, fused add+LN, "
               "fused conv+BN) consult the per-chip tuning cache "
               "(~/.cache/paddle_tpu/autotune/<chip>.json overlaid on "
               "the checked-in paddle_tpu/tuning/defaults, "
               "$PADDLE_AUTOTUNE_CACHE pins an explicit file) for their "
               "tile/block configs at trace time; a missing entry falls "
               "back to the hand-picked chooser (no behavior cliff). "
               "The active cache fingerprint rides the Executor "
               "compile-cache key so editing the cache retraces. Off = "
               "no lookup runs and emitted programs are bit-identical "
               "to a build without the tuning layer. Search/inspect: "
               "tools/autotune.py"),
    "FLAGS_dataloader_require_spawn": (
        False, "fluid/dataloader: raise instead of warning when worker "
               "args are unpicklable and the loader would fall back to "
               "fork() (which can deadlock under the multithreaded JAX "
               "runtime) — the production-config hard-fail"),
    # --- parity, inert on TPU (subsumed) ---
    "FLAGS_allocator_strategy": ("naive_best_fit", None),  # PJRT allocator
    "FLAGS_fraction_of_gpu_memory_to_use": (0.92, None),
    "FLAGS_eager_delete_tensor_gb": (0.0, None),  # XLA buffer liveness
    "FLAGS_fuse_parameter_memory_size": (-1, None),  # XLA fusion
    "FLAGS_cudnn_deterministic": (False, None),  # XLA is deterministic
    "FLAGS_cpu_deterministic": (False, None),
    "FLAGS_paddle_num_threads": (1, None),  # XLA threadpool
    "FLAGS_inner_op_parallelism": (0, None),
    "FLAGS_sync_nccl_allreduce": (True, None),  # ICI collectives
    "FLAGS_enable_parallel_graph": (False, None),  # GSPMD
}

_values: Dict[str, Any] = {}


def _coerce(default, raw: str):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _init_from_env():
    for name, (default, _) in _DEFS.items():
        raw = os.environ.get(name)
        _values[name] = _coerce(default, raw) if raw is not None else default


_init_from_env()


def get_flags(flags):
    """reference fluid.get_flags: str or list -> {flag: value}."""
    names = [flags] if isinstance(flags, str) else list(flags)
    out = {}
    for n in names:
        if n not in _values:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = _values[n]
    return out


def set_flags(flags: Dict[str, Any]):
    """reference fluid.set_flags."""
    for n, v in flags.items():
        if n not in _values:
            raise ValueError(f"unknown flag {n!r}")
        default = _DEFS[n][0]
        _values[n] = _coerce(default, v) if isinstance(v, str) else type(default)(v)


def flag(name: str):
    return _values[name]
