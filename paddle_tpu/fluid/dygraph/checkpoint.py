"""save_dygraph / load_dygraph.

Parity: /root/reference/python/paddle/fluid/dygraph/checkpoint.py —
state_dict pickling with the .pdparams/.pdopt extension convention.
"""
from __future__ import annotations

import os
import pickle

import numpy as np


def save_dygraph(state_dict, model_path: str) -> None:
    suffix = ".pdparams"
    if state_dict and all(
        isinstance(v, dict) for v in state_dict.values() if v is not None
    ):
        # optimizer state dicts nest per-param dicts
        suffix = ".pdopt"
    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = np.asarray(v) if not isinstance(v, dict) else {
            kk: np.asarray(vv) for kk, vv in v.items()
        }
    os.makedirs(os.path.dirname(os.path.abspath(model_path)) or ".", exist_ok=True)
    # tmp + os.replace, same contract as every fluid/io.py save path: a
    # crash mid-save can never leave a torn .pdparams/.pdopt for the
    # next load_dygraph to choke on — it sees the complete old file or
    # the complete new one
    from ..io import _atomic_write_bytes

    _atomic_write_bytes(model_path + suffix, pickle.dumps(arrays))


def load_dygraph(model_path: str):
    params, opt = None, None
    if os.path.exists(model_path + ".pdparams"):
        with open(model_path + ".pdparams", "rb") as f:
            params = pickle.load(f)
    if os.path.exists(model_path + ".pdopt"):
        with open(model_path + ".pdopt", "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise ValueError(f"no checkpoint found at {model_path!r}")
    return params, opt
