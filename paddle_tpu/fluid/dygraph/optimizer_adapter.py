"""Eager (dygraph) optimizer stepping.

Parity: the reference shares optimizer *ops* between static and dygraph
modes (dygraph traces adam ops eagerly through the same OpKernel registry,
imperative/tracer.cc). Here likewise: the same registered update emitters
(ops/optimizer_ops.py) are invoked eagerly on VarBase values; accumulator
state lives on the Optimizer instance keyed by parameter name.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ...ops import registry


def _zeros_like(p):
    import jax.numpy as jnp

    return jnp.zeros_like(p.value)


def _scalar(v):
    import jax.numpy as jnp

    return jnp.full((1,), v, jnp.float32)


# type -> (state spec: name -> init(p, opt), ins builder, attrs builder,
#          out-slot -> state-name bindings)
_SPECS = {
    "sgd": (
        {},
        lambda p, g, st, o: {"Param": [p.value], "Grad": [g], "LearningRate": [o._lr_value()]},
        lambda o: {},
        {"ParamOut": "__param__"},
    ),
    "momentum": (
        {"velocity": lambda p, o: _zeros_like(p)},
        lambda p, g, st, o: {
            "Param": [p.value], "Grad": [g], "Velocity": [st["velocity"]],
            "LearningRate": [o._lr_value()],
        },
        lambda o: {"mu": o._momentum, "use_nesterov": getattr(o, "_use_nesterov", False)},
        {"ParamOut": "__param__", "VelocityOut": "velocity"},
    ),
    "adam": (
        {
            "moment1": lambda p, o: _zeros_like(p),
            "moment2": lambda p, o: _zeros_like(p),
            "beta1_pow": lambda p, o: _scalar(o._beta1),
            "beta2_pow": lambda p, o: _scalar(o._beta2),
        },
        lambda p, g, st, o: {
            "Param": [p.value], "Grad": [g],
            "Moment1": [st["moment1"]], "Moment2": [st["moment2"]],
            "Beta1Pow": [st["beta1_pow"]], "Beta2Pow": [st["beta2_pow"]],
            "LearningRate": [o._lr_value()],
        },
        lambda o: {"beta1": o._beta1, "beta2": o._beta2, "epsilon": o._epsilon},
        {
            "ParamOut": "__param__", "Moment1Out": "moment1", "Moment2Out": "moment2",
            "Beta1PowOut": "beta1_pow", "Beta2PowOut": "beta2_pow",
        },
    ),
    "adagrad": (
        {"moment": lambda p, o: _zeros_like(p)},
        lambda p, g, st, o: {
            "Param": [p.value], "Grad": [g], "Moment": [st["moment"]],
            "LearningRate": [o._lr_value()],
        },
        lambda o: {"epsilon": o._epsilon},
        {"ParamOut": "__param__", "MomentOut": "moment"},
    ),
    "rmsprop": (
        {
            "mean_square": lambda p, o: _zeros_like(p),
            "mean_grad": lambda p, o: _zeros_like(p),
            "momentum": lambda p, o: _zeros_like(p),
        },
        lambda p, g, st, o: {
            "Param": [p.value], "Grad": [g],
            "MeanSquare": [st["mean_square"]], "MeanGrad": [st["mean_grad"]],
            "Moment": [st["momentum"]], "LearningRate": [o._lr_value()],
        },
        lambda o: {
            "epsilon": o._epsilon, "decay": o._rho, "momentum": o._momentum,
            "centered": getattr(o, "_centered", False),
        },
        {
            "ParamOut": "__param__", "MeanSquareOut": "mean_square",
            "MeanGradOut": "mean_grad", "MomentOut": "momentum",
        },
    ),
}
_SPECS["adamw"] = _SPECS["adam"]
_SPECS["lamb"] = (
    _SPECS["adam"][0],
    _SPECS["adam"][1],
    lambda o: {
        "beta1": o._beta1, "beta2": o._beta2, "epsilon": o._epsilon,
        "weight_decay": getattr(o, "_lamb_weight_decay", 0.01),
    },
    _SPECS["adam"][3],
)


def dygraph_step(optimizer, params) -> None:
    """Apply one eager update to every param carrying a gradient."""
    spec = registry.get(optimizer.type)
    table = _SPECS.get(optimizer.type)
    if table is None:
        raise NotImplementedError(
            f"dygraph mode: optimizer {optimizer.type!r} has no eager adapter"
        )
    state_spec, ins_fn, attrs_fn, out_bind = table
    if not hasattr(optimizer, "_eager_state"):
        optimizer._eager_state: Dict[str, Dict] = {}
    if optimizer.type == "adamw":
        attrs = attrs_fn(optimizer)
        attrs["coeff"] = optimizer._weight_decay
    else:
        attrs = attrs_fn(optimizer)
    ctx = registry.EmitContext()
    for p in params:
        if p.grad is None or p.stop_gradient:
            continue
        st = optimizer._eager_state.setdefault(
            p.name, {k: f(p, optimizer) for k, f in state_spec.items()}
        )
        outs = spec.emit(ctx, ins_fn(p, p.grad, st, optimizer), attrs)
        for slot, target in out_bind.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            if target == "__param__":
                p.value = vals[0]
            else:
                st[target] = vals[0]
