"""Dygraph data parallelism (reference fluid/dygraph/parallel.py:
DataParallel:225, scale_loss:292, apply_collective_grads:384,
prepare_context, ParallelEnv).

TPU-native: the reference coalesces gradients and calls NCCL allreduce
per bucket. Here the collective is one jax psum over the launcher-created
process group (paddle_tpu.distributed); buckets are unnecessary — XLA
fuses the flat gradient tree into as few transfers as ICI needs. On a
single process the wrapper is a transparent no-op, matching the
reference's nranks==1 fast path.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ...parallel.env import get_rank, get_world_size, init_parallel_env
from .layers import Layer


class ParallelEnv:
    """reference dygraph/parallel.py Env: rank/world from launcher env."""

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return get_rank()

    @property
    def dev_id(self) -> int:
        return 0  # one logical device per process under PJRT


Env = ParallelEnv


def prepare_context(strategy=None):
    """Initialize the coordination service (replaces NCCL context init)."""
    init_parallel_env()
    return ParallelEnv()


def _default_comm(grad):
    """Sum one gradient across processes, eagerly (outside any mapped
    computation). scale_loss already divided the loss by nranks, so the
    summed gradient IS the global mean — no second division."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(grad[None], tiled=True).sum(axis=0)


class DataParallel(Layer):
    """Wraps a Layer for multi-process data-parallel training.

    forward delegates to the wrapped layer; after loss.backward(), call
    apply_collective_grads() to mean-allreduce every parameter gradient
    (reference apply_collective_grads:384). scale_loss divides by nranks
    so the summed allreduce yields the global mean (reference :292).

    comm: injectable per-gradient collective (tests exercise the
    averaging path without a multi-process launch).
    """

    def __init__(self, layers: Layer, strategy=None,
                 comm: Optional[Callable] = None):
        super().__init__()
        self._layers = layers
        self._comm = comm
        self._nranks = get_world_size()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix=""):
        return self._layers.named_parameters(prefix)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    def scale_loss(self, loss):
        if self._nranks <= 1 and self._comm is None:
            return loss
        n = self._nranks if self._nranks > 1 else 1
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        if self._nranks <= 1 and self._comm is None:
            return  # single process: nothing to average
        comm = self._comm or _default_comm
        for p in self._layers.parameters():
            if p.grad is None:
                continue
            p.grad = comm(p.grad)


class LocalSGD:
    """LocalSGD for multi-process dygraph training (reference
    transpiler/collective.py:270 LocalSGD transpile: train k steps on
    LOCAL gradients, then average parameters across workers).

    This lives on the dygraph path because it is the one place per-worker
    divergent parameters exist: the GSPMD static executor keeps params
    replicated by construction (fleet raises for strategy.localsgd and
    points here).

        dp = DataParallel(net)            # no per-step grad allreduce
        lsgd = LocalSGD(dp, k_steps=4)
        for batch in data:
            loss = ...; loss.backward()
            opt.minimize(loss); net.clear_gradients()
            lsgd.step()                   # averages params every k steps

    comm: injectable per-tensor mean (tests); defaults to the
    process_allgather mean across workers.
    """

    def __init__(self, layers: Layer, k_steps: int = 1,
                 comm: Optional[Callable] = None):
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._layers = layers
        self._k = int(k_steps)
        self._comm = comm
        self._step = 0

    def _average(self, value):
        if self._comm is not None:
            return self._comm(value)
        if get_world_size() <= 1:
            return value
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(
            value[None], tiled=True
        ).mean(axis=0)

    def step(self) -> bool:
        """Call once per optimizer step; averages parameters on every
        k-th call. Returns True when a sync happened."""
        self._step += 1
        if self._step % self._k != 0:
            return False
        for p in self._layers.parameters():
            p.value = self._average(p.value)
        return True
