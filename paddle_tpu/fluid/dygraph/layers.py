"""Dygraph Layer base class.

Parity surface: /root/reference/python/paddle/fluid/dygraph/layers.py
(Layer: parameters, sublayers, state_dict, train/eval, __call__).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import unique_name
from ..initializer import ConstantInitializer, XavierInitializer
from ..param_attr import ParamAttr
from .base import VarBase


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower()
        )
        self._dtype = dtype
        self.training = True
        self._parameters: "OrderedDict[str, VarBase]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, VarBase]" = OrderedDict()

    # -- construction ----------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> VarBase:
        attr = ParamAttr._to_attr(attr)
        dtype = dtype or self._dtype
        init = (
            default_initializer
            or (attr.initializer if attr is not None and attr.initializer else None)
            or (ConstantInitializer(0.0) if is_bias else XavierInitializer())
        )
        value = _init_numpy(init, shape, dtype)
        name = attr.name if attr and attr.name else unique_name.generate(
            f"{self._full_name}.{'b' if is_bias else 'w'}"
        )
        p = VarBase(value, name=name, persistable=True)
        p.stop_gradient = not (attr.trainable if attr else True)
        p.is_parameter = True
        return p

    def add_parameter(self, name: str, parameter: VarBase) -> VarBase:
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, value: VarBase):
        value.stop_gradient = True
        self._buffers[name] = value
        return value

    # -- attribute protocol (auto-register params/sublayers) -------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, VarBase) and getattr(value, "is_parameter", False):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ first")
            subs[name] = value
            self.__dict__.pop(name, None)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{self.__class__.__name__} has no attribute {name!r}")

    # -- traversal -------------------------------------------------------
    def named_parameters(self, prefix="") -> Iterator[Tuple[str, VarBase]]:
        for n, p in self._parameters.items():
            yield (f"{prefix}.{n}" if prefix else n), p
        for sn, sub in self._sub_layers.items():
            yield from sub.named_parameters(f"{prefix}.{sn}" if prefix else sn)

    def parameters(self, include_sublayers=True) -> List[VarBase]:
        return [p for _, p in self.named_parameters()]

    def sublayers(self, include_self=False) -> List["Layer"]:
        out = [self] if include_self else []
        for s in self._sub_layers.values():
            out.append(s)
            out.extend(s.sublayers())
        return out

    def train(self):
        self.training = True
        for s in self._sub_layers.values():
            s.train()
        return self

    def eval(self):
        self.training = False
        for s in self._sub_layers.values():
            s.eval()
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- state dict ------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, prefix=""):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters():
            dest[name] = p.numpy()
        for store in ("_buffers",):
            for n, b in getattr(self, store).items():
                dest[n] = b.numpy()
        return dest

    def set_dict(self, state_dict, include_sublayers=True):
        import jax.numpy as jnp

        named = dict(self.named_parameters())
        for k, v in state_dict.items():
            if k in named:
                named[k].value = jnp.asarray(v)
            elif k in self._buffers:
                self._buffers[k].value = jnp.asarray(v)

    load_dict = set_dict

    # -- call ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    @property
    def full_name(self):
        return self._full_name


def _init_numpy(initializer, shape, dtype) -> np.ndarray:
    """Evaluate a static-graph Initializer eagerly: run its op emitter on
    a scratch block-free path (initializers only need shape/dtype)."""
    from ..initializer import (
        BilinearInitializer,
        ConstantInitializer,
        MSRAInitializer,
        NormalInitializer,
        NumpyArrayInitializer,
        TruncatedNormalInitializer,
        UniformInitializer,
        XavierInitializer,
    )

    rng = np.random
    shape = tuple(int(s) for s in shape)
    if isinstance(initializer, ConstantInitializer):
        return np.full(shape, initializer.value, dtype=dtype)
    if isinstance(initializer, NumpyArrayInitializer):
        return np.asarray(initializer.value, dtype=dtype).reshape(shape)
    if isinstance(initializer, UniformInitializer):
        return rng.uniform(initializer.low, initializer.high, shape).astype(dtype)
    if isinstance(initializer, TruncatedNormalInitializer):
        a = rng.normal(initializer.loc, initializer.scale, shape)
        lim = 2 * initializer.scale
        return np.clip(a, initializer.loc - lim, initializer.loc + lim).astype(dtype)
    if isinstance(initializer, NormalInitializer):
        return rng.normal(initializer.loc, initializer.scale, shape).astype(dtype)
    if isinstance(initializer, (XavierInitializer, MSRAInitializer)):
        fan_in = shape[0] if len(shape) > 1 else max(shape[0], 1)
        fan_out = shape[1] if len(shape) > 1 else max(shape[0], 1)
        if getattr(initializer, "uniform", True):
            limit = np.sqrt(6.0 / (fan_in + fan_out))
            return rng.uniform(-limit, limit, shape).astype(dtype)
        std = np.sqrt(2.0 / (fan_in + fan_out))
        return rng.normal(0.0, std, shape).astype(dtype)
    raise TypeError(f"unsupported initializer for dygraph: {initializer!r}")
