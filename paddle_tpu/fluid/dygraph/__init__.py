"""Dygraph (imperative/eager) mode.

Parity surface: /root/reference/python/paddle/fluid/dygraph/ — guard,
to_variable, Layer, nn layers, no_grad, save/load_dygraph, jit tracing.
Eager execution runs the same op emitters per-op under jax (each gets
jax's own per-op jit cache); training at scale should use the static
Program path, which compiles whole steps (reference parity: dygraph is
the development/debug mode there too).
"""
from . import jit, nn, parallel  # noqa: F401
from .jit import (  # noqa: F401
    ProgramTranslator,
    TracedLayer,
    TranslatedLayer,
    declarative,
    to_static,
)
from .parallel import DataParallel, LocalSGD, ParallelEnv, prepare_context  # noqa: F401
from .base import (  # noqa: F401
    VarBase,
    Tracer,
    enabled,
    guard,
    no_grad,
    to_variable,
)
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from .layers import Layer  # noqa: F401
from .nn import (  # noqa: F401
    NCE,
    BatchNorm,
    BilinearTensorProduct,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
    Dropout,
    Embedding,
    GroupNorm,
    GRUUnit,
    InstanceNorm,
    LayerList,
    LayerNorm,
    Linear,
    ParameterList,
    Pool2D,
    PRelu,
    RowConv,
    Sequential,
    SequenceConv,
    SpectralNorm,
    TreeConv,
)
