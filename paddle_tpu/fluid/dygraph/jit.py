"""dygraph-to-static: trace eager Layers/functions into static Programs.

Parity surface: reference fluid/dygraph/jit.py (TracedLayer, trace),
dygraph_to_static/program_translator.py:348 (ProgramTranslator,
get_program:541), and the @declarative/to_static decorator.

TPU-native design: the reference transpiles Python AST (15 transformer
files) because its dygraph ops are opaque C++ calls. Here every dygraph
op already funnels through Tracer.trace_op, so dygraph-to-static is a
TRACER SWAP: a ProgramTracer records each traced op into a Program
instead of executing it eagerly — the same mechanism JAX uses for jit.
Python control flow is resolved at trace time (like jax.jit); the
static-graph layers.cond/while_loop remain the tool for data-dependent
control flow, exactly as with jax.lax.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import framework
from .. import unique_name
from . import base
from .base import Tracer, VarBase


# out slot -> in slot pairs the op updates IN PLACE (the reference op
# descs alias these; the traced program must write back to the same var
# so persistable state advances and syncs to the eager buffers)
_INPLACE_SLOTS = {
    "batch_norm": {"MeanOut": "Mean", "VarianceOut": "Variance"},
}


class ProgramTracer(Tracer):
    """Tracer that builds a static Program from dygraph op calls."""

    def __init__(self, program: framework.Program, startup: framework.Program):
        super().__init__()
        self.program = program
        self.startup = startup
        self.param_values: Dict[str, np.ndarray] = {}
        # live VarBase behind each traced parameter: calls re-seed the
        # scope from (and write updates back to) the eager tensors, so
        # parameters are SHARED with dygraph, not frozen at trace time
        self.param_sources: Dict[str, Any] = {}
        self._var_map: Dict[int, framework.Variable] = {}

    # -- VarBase -> static Variable ------------------------------------
    def lift(self, v):
        if isinstance(v, framework.Variable):
            return v
        sv = self._var_map.get(id(v))
        if sv is None:
            # leaf VarBase (a Layer parameter or captured constant): a
            # persistable var whose current value seeds the scope
            block = self.program.global_block()
            name = unique_name.generate("traced_param")
            if v.stop_gradient:
                sv = block.create_var(
                    name=name, shape=tuple(v.shape), dtype=np.dtype(str(v.dtype)),
                    persistable=True,
                )
                sv.stop_gradient = True
            else:
                sv = framework.Parameter(
                    block, name, shape=tuple(v.shape),
                    dtype=np.dtype(str(v.dtype)),
                )
                block.vars[name] = sv
            self.param_values[name] = np.asarray(v.value)
            self.param_sources[name] = v
            self._var_map[id(v)] = sv
        return sv

    def trace_op(self, type, inputs, attrs, out_slots):
        block = self.program.global_block()
        in_names: Dict[str, List[str]] = {}
        for slot, vs in inputs.items():
            if vs:
                in_names[slot] = [self.lift(v).name for v in vs]
        inplace = _INPLACE_SLOTS.get(type, {})
        out_names: Dict[str, List[str]] = {}
        outputs: Dict[str, List[framework.Variable]] = {}
        for slot in out_slots:
            src_slot = inplace.get(slot)
            if src_slot and in_names.get(src_slot):
                # write back onto the input var: running state advances
                # inside the program and syncs to the eager buffer via
                # parameter_sources
                out_names[slot] = [in_names[src_slot][0]]
                continue
            n = unique_name.generate(f"traced_{type}_{slot}")
            block.create_var(name=n)
            out_names[slot] = [n]
        block.append_op(type=type, inputs=in_names, outputs=out_names, attrs=dict(attrs))
        for slot in out_slots:
            outputs[slot] = [block.var(out_names[slot][0])]
        return outputs


class ConcreteProgram:
    """The result of one trace: program + endpoints + parameter seeds."""

    def __init__(self, main, startup, feed_vars, fetch_vars, param_values,
                 param_sources=None):
        self.main_program = main
        self.startup_program = startup
        self.inputs = feed_vars
        self.outputs = fetch_vars
        self.parameter_values = param_values
        # name -> live VarBase (two-way parameter sharing with dygraph)
        self.parameter_sources = param_sources or {}


def _trace(fn, example_inputs) -> Tuple[List[Any], ConcreteProgram]:
    main, startup = framework.Program(), framework.Program()
    tracer = ProgramTracer(main, startup)
    feed_vars = []
    with framework.program_guard(main, startup):
        block = main.global_block()
        args = []
        for i, a in enumerate(example_inputs):
            arr = np.asarray(a.value if isinstance(a, VarBase) else a)
            v = block.create_var(name=f"traced_in_{i}", shape=arr.shape, dtype=arr.dtype)
            v.stop_gradient = arr.dtype.kind != "f"
            feed_vars.append(v)
            args.append(v)
        old = framework._dygraph_tracer_
        framework._dygraph_tracer_ = tracer
        try:
            outs = fn(*args)
        finally:
            framework._dygraph_tracer_ = old
    outs_list = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    fetch_vars = [tracer.lift(o) for o in outs_list]
    cp = ConcreteProgram(
        main, startup, feed_vars, fetch_vars, tracer.param_values,
        tracer.param_sources,
    )
    return outs_list, cp


class StaticFunction:
    """@to_static-wrapped callable (reference StaticFunction /
    program_translator.get_output:440). Traces once per input signature,
    then runs the compiled Program through an Executor."""

    def __init__(self, fn):
        self._fn = fn
        # AST conversion first (reference ast_transformer.py): tensor-
        # condition if/while/for-range become cond/while_loop ops instead
        # of being baked to the traced branch; unparseable sources fall
        # back to the plain trace
        from .dygraph_to_static import ast_to_static

        self._ast_fn = ast_to_static(fn)
        self._cache: Dict[tuple, tuple] = {}
        from ..executor import Executor, Scope

        self._exe = Executor()
        self._scope = Scope()

    def _sig(self, args):
        out = []
        for a in args:
            v = a.value if isinstance(a, VarBase) else a
            shape = getattr(v, "shape", None)
            dtype = getattr(v, "dtype", None)
            if shape is None or dtype is None:
                v = np.asarray(v)
                shape, dtype = v.shape, v.dtype
            out.append((tuple(shape), str(dtype)))
        return tuple(out)

    def get_concrete_program(self, *args) -> ConcreteProgram:
        key = self._sig(args)
        if key not in self._cache:
            _, cp = _trace(self._ast_fn, args)
            self._cache[key] = cp
        return self._cache[key]

    def __call__(self, *args):
        if not ProgramTranslator.get_instance().enabled:
            # disabled translator: run the original function eagerly
            # (reference program_translator semantics for debugging)
            return self._fn(*args)
        from .. import executor as executor_mod

        cp = self.get_concrete_program(*args)
        with executor_mod.scope_guard(self._scope):
            scope = executor_mod.global_scope()
            # parameters are shared with dygraph: push the CURRENT eager
            # values in, and pull any in-program updates back out after
            for name, vb in cp.parameter_sources.items():
                scope.set_var(name, vb.value)
            feed = {
                v.name: np.asarray(a.value if isinstance(a, VarBase) else a)
                for v, a in zip(cp.inputs, args)
            }
            outs = self._exe.run(
                cp.main_program, feed=feed,
                fetch_list=[v.name for v in cp.outputs],
            )
            for name, vb in cp.parameter_sources.items():
                new = scope.find_var(name)
                if new is not None:
                    vb.value = new
        outs = [VarBase(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def to_static(fn=None):
    """Decorator (reference @declarative / paddle.jit.to_static)."""
    if fn is None:
        return to_static
    return StaticFunction(fn)


declarative = to_static


class TracedLayer:
    """reference fluid/dygraph/jit.py TracedLayer: trace a Layer once,
    run / save the resulting Program."""

    def __init__(self, cp: ConcreteProgram):
        self.concrete_program = cp
        from ..executor import Executor, Scope

        self._exe = Executor()
        self._scope = Scope()

    @staticmethod
    def trace(layer, inputs: Sequence) -> Tuple[Any, "TracedLayer"]:
        outs, cp = _trace(lambda *a: layer(*a), list(inputs))
        # re-run eagerly for the first return value (reference returns the
        # dygraph outputs of this call)
        eager_outs = layer(*inputs)
        return eager_outs, TracedLayer(cp)

    @property
    def program(self):
        return self.concrete_program.main_program

    def _seed_scope(self):
        from .. import executor as executor_mod

        scope = executor_mod.global_scope()
        for name, val in self.concrete_program.parameter_values.items():
            if scope.find_var(name) is None:
                scope.set_var(name, val)

    def __call__(self, inputs: Sequence):
        from .. import executor as executor_mod

        cp = self.concrete_program
        with executor_mod.scope_guard(self._scope):
            self._seed_scope()
            feed = {
                v.name: np.asarray(a.value if isinstance(a, VarBase) else a)
                for v, a in zip(cp.inputs, inputs)
            }
            outs = self._exe.run(
                cp.main_program, feed=feed,
                fetch_list=[v.name for v in cp.outputs],
            )
        return [VarBase(o) for o in outs]

    def save_inference_model(self, path, feed=None, fetch=None,
                             encrypt_key=None):
        from .. import executor as executor_mod
        from .. import io

        cp = self.concrete_program
        with executor_mod.scope_guard(self._scope):
            self._seed_scope()
            io.save_inference_model(
                path,
                [v.name for v in cp.inputs],
                cp.outputs,
                self._exe,
                main_program=cp.main_program,
                encrypt_key=encrypt_key,
            )


class ProgramTranslator:
    """Singleton facade (reference program_translator.py:348)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enabled = True
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static: bool):
        self.enabled = bool(enable_to_static)

    def get_program(self, fn, *args):
        """Trace fn with args -> (main_program, startup_program, inputs,
        outputs) (reference get_program:541)."""
        sf = fn if isinstance(fn, StaticFunction) else StaticFunction(fn)
        cp = sf.get_concrete_program(*args)
        return cp.main_program, cp.startup_program, cp.inputs, cp.outputs

    def get_output(self, fn, *args):
        sf = fn if isinstance(fn, StaticFunction) else StaticFunction(fn)
        return sf(*args)


class TranslatedLayer:
    """A saved inference model callable from dygraph (reference
    fluid/dygraph/io.py TranslatedLayer, returned by jit.load)."""

    def __init__(self, dirname, model_filename=None, params_filename=None,
                 decrypt_key=None):
        from .. import executor as executor_mod
        from .. import io
        from ..executor import Executor, Scope

        self._exe = Executor()
        self._scope = Scope()
        with executor_mod.scope_guard(self._scope):
            prog, feeds, fetches = io.load_inference_model(
                dirname, self._exe, model_filename=model_filename,
                params_filename=params_filename, decrypt_key=decrypt_key,
            )
        self._program = prog
        self._feed_names = list(feeds)
        self._fetch_names = [v.name for v in fetches]

    def __call__(self, *inputs):
        from .. import executor as executor_mod

        if len(inputs) != len(self._feed_names):
            raise ValueError(
                f"expected {len(self._feed_names)} inputs "
                f"({self._feed_names}), got {len(inputs)}"
            )
        feed = {
            n: np.asarray(a.value if isinstance(a, VarBase) else a)
            for n, a in zip(self._feed_names, inputs)
        }
        with executor_mod.scope_guard(self._scope):
            outs = self._exe.run(
                self._program, feed=feed, fetch_list=self._fetch_names
            )
        outs = [VarBase(o, stop_gradient=True) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def eval(self):
        return self

    def train(self):
        raise NotImplementedError(
            "TranslatedLayer is inference-only (the saved model is the "
            "pruned forward graph); retrain from the original Layer"
        )


def load(dirname, model_filename=None, params_filename=None,
         decrypt_key=None):
    """jit.load (reference fluid/dygraph/jit.py load / io.py
    TranslatedLayer): load a saved inference model as a callable."""
    return TranslatedLayer(dirname, model_filename, params_filename,
                           decrypt_key=decrypt_key)


def save(layer, path, input_spec=None, encrypt_key=None):
    """jit.save: trace (if needed) and export (reference jit.save).
    `layer` is a TracedLayer (already traced) or a dygraph Layer plus
    input_spec example inputs. encrypt_key pairs with
    jit.load(..., decrypt_key=...)."""
    if isinstance(layer, TracedLayer):
        layer.save_inference_model(path, encrypt_key=encrypt_key)
        return
    if input_spec is None:
        raise ValueError("jit.save needs input_spec examples for a raw Layer")
    # trace directly: TracedLayer.trace would also run a redundant eager
    # forward just to return outputs that save discards
    _, cp = _trace(lambda *a: layer(*a), list(input_spec))
    TracedLayer(cp).save_inference_model(path, encrypt_key=encrypt_key)
