"""Dygraph nn layer classes.

Parity surface: /root/reference/python/paddle/fluid/dygraph/nn.py
(Linear, Conv2D, Pool2D, Embedding, LayerNorm, BatchNorm, Dropout, GRUUnit...).
Each forward traces the same registered op emitters as the static graph.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..initializer import ConstantInitializer
from ..param_attr import ParamAttr
from .base import VarBase, _trace_op
from .layers import Layer


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim], attr=ParamAttr._to_attr(param_attr))
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([output_dim], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        )
        self._act = act

    def forward(self, x):
        out = _trace_op(
            "mul", {"X": [x], "Y": [self.weight]},
            {"x_num_col_dims": len(x.shape) - 1, "y_num_col_dims": 1}, ["Out"]
        )[0]
        if self.bias is not None:
            out = _trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": -1}, ["Out"]
            )[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(list(size), attr=ParamAttr._to_attr(param_attr))
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        return _trace_op(
            "lookup_table_v2",
            {"W": [self.weight], "Ids": [ids]},
            {"padding_idx": self._padding_idx},
            ["Out"],
        )[0]


class Conv2D(Layer):
    def __init__(
        self, num_channels, num_filters, filter_size, stride=1, padding=0,
        dilation=1, groups=1, param_attr=None, bias_attr=None, act=None, dtype="float32",
    ):
        super().__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
        self._attrs = {
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
            "groups": groups or 1,
        }
        self.weight = self.create_parameter(
            [num_filters, num_channels // (groups or 1)] + fs,
            attr=ParamAttr._to_attr(param_attr),
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([num_filters], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        )
        self._act = act

    def forward(self, x):
        out = _trace_op(
            "conv2d", {"Input": [x], "Filter": [self.weight]}, self._attrs, ["Output"]
        )[0]
        if self.bias is not None:
            out = _trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}, ["Out"]
            )[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0, global_pooling=False, ceil_mode=False):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
        }

    def forward(self, x):
        return _trace_op("pool2d", {"X": [x]}, self._attrs, ["Out"])[0]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter(
                normalized_shape, attr=ParamAttr._to_attr(param_attr),
                default_initializer=ConstantInitializer(1.0),
            )
            if scale
            else None
        )
        self.bias = (
            self.create_parameter(normalized_shape, attr=ParamAttr._to_attr(bias_attr), is_bias=True)
            if shift
            else None
        )
        self._norm_ndim = len(normalized_shape)

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        return _trace_op(
            "layer_norm",
            ins,
            {"epsilon": self._epsilon, "begin_norm_axis": len(x.shape) - self._norm_ndim},
            ["Y"],
        )[0]


class BatchNorm(Layer):
    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32", data_layout="NCHW"):
        super().__init__(dtype=dtype)
        self._momentum, self._epsilon, self._layout = momentum, epsilon, data_layout
        self.weight = self.create_parameter(
            [num_channels], attr=ParamAttr._to_attr(param_attr),
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter([num_channels], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        self._mean = self.register_buffer("_mean", VarBase(np.zeros(num_channels, dtype), persistable=True))
        self._variance = self.register_buffer("_variance", VarBase(np.ones(num_channels, dtype), persistable=True))

    def forward(self, x):
        outs = _trace_op(
            "batch_norm",
            {
                "X": [x],
                "Scale": [self.weight],
                "Bias": [self.bias],
                "Mean": [self._mean],
                "Variance": [self._variance],
            },
            {
                "momentum": self._momentum,
                "epsilon": self._epsilon,
                "data_layout": self._layout,
                "is_test": not self.training,
            },
            ["Y", "MeanOut", "VarianceOut"],
        )
        y, mean_out, var_out = outs[0], outs[1], outs[2]
        # running stats update (buffers are plain values, not graph
        # state); under the ProgramTracer the outputs are static
        # Variables — the traced program carries the stats through the
        # batch_norm op itself, so no eager assignment happens
        if isinstance(mean_out, VarBase):
            self._mean.value = mean_out.value
            self._variance.value = var_out.value
        return y


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, x):
        return _trace_op(
            "dropout",
            {"X": [x]},
            {
                "dropout_prob": self._p,
                "is_test": not self.training,
                "dropout_implementation": self._impl,
            },
            ["Out"],
        )[0]
