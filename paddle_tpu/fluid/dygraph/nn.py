"""Dygraph nn layer classes.

Parity surface: /root/reference/python/paddle/fluid/dygraph/nn.py
(Linear, Conv2D, Pool2D, Embedding, LayerNorm, BatchNorm, Dropout, GRUUnit...).
Each forward traces the same registered op emitters as the static graph.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..initializer import ConstantInitializer
from ..param_attr import ParamAttr
from .base import VarBase, _trace_op
from .layers import Layer


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim], attr=ParamAttr._to_attr(param_attr))
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([output_dim], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        )
        self._act = act

    def forward(self, x):
        out = _trace_op(
            "mul", {"X": [x], "Y": [self.weight]},
            {"x_num_col_dims": len(x.shape) - 1, "y_num_col_dims": 1}, ["Out"]
        )[0]
        if self.bias is not None:
            out = _trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": -1}, ["Out"]
            )[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(list(size), attr=ParamAttr._to_attr(param_attr))
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        return _trace_op(
            "lookup_table_v2",
            {"W": [self.weight], "Ids": [ids]},
            {"padding_idx": self._padding_idx},
            ["Out"],
        )[0]


class Conv2D(Layer):
    def __init__(
        self, num_channels, num_filters, filter_size, stride=1, padding=0,
        dilation=1, groups=1, param_attr=None, bias_attr=None, act=None, dtype="float32",
    ):
        super().__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
        self._attrs = {
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
            "groups": groups or 1,
        }
        self.weight = self.create_parameter(
            [num_filters, num_channels // (groups or 1)] + fs,
            attr=ParamAttr._to_attr(param_attr),
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([num_filters], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        )
        self._act = act

    def forward(self, x):
        out = _trace_op(
            "conv2d", {"Input": [x], "Filter": [self.weight]}, self._attrs, ["Output"]
        )[0]
        if self.bias is not None:
            out = _trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}, ["Out"]
            )[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0, global_pooling=False, ceil_mode=False):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
        }

    def forward(self, x):
        return _trace_op("pool2d", {"X": [x]}, self._attrs, ["Out"])[0]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter(
                normalized_shape, attr=ParamAttr._to_attr(param_attr),
                default_initializer=ConstantInitializer(1.0),
            )
            if scale
            else None
        )
        self.bias = (
            self.create_parameter(normalized_shape, attr=ParamAttr._to_attr(bias_attr), is_bias=True)
            if shift
            else None
        )
        self._norm_ndim = len(normalized_shape)

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        return _trace_op(
            "layer_norm",
            ins,
            {"epsilon": self._epsilon, "begin_norm_axis": len(x.shape) - self._norm_ndim},
            ["Y"],
        )[0]


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32", data_layout="NCHW"):
        super().__init__(dtype=dtype)
        self._momentum, self._epsilon, self._layout = momentum, epsilon, data_layout
        self._act = act
        self.weight = self.create_parameter(
            [num_channels], attr=ParamAttr._to_attr(param_attr),
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter([num_channels], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        self._mean = self.register_buffer("_mean", VarBase(np.zeros(num_channels, dtype), persistable=True))
        self._variance = self.register_buffer("_variance", VarBase(np.ones(num_channels, dtype), persistable=True))

    def forward(self, x):
        outs = _trace_op(
            "batch_norm",
            {
                "X": [x],
                "Scale": [self.weight],
                "Bias": [self.bias],
                "Mean": [self._mean],
                "Variance": [self._variance],
            },
            {
                "momentum": self._momentum,
                "epsilon": self._epsilon,
                "data_layout": self._layout,
                "is_test": not self.training,
            },
            ["Y", "MeanOut", "VarianceOut"],
        )
        y, mean_out, var_out = outs[0], outs[1], outs[2]
        # running stats update (buffers are plain values, not graph
        # state); under the ProgramTracer the outputs are static
        # Variables — the traced program carries the stats through the
        # batch_norm op itself, so no eager assignment happens
        if isinstance(mean_out, VarBase):
            self._mean.value = mean_out.value
            self._variance.value = var_out.value
        if self._act:
            y = _trace_op(self._act, {"X": [y]}, {}, ["Out"])[0]
        return y


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, x):
        return _trace_op(
            "dropout",
            {"X": [x]},
            {
                "dropout_prob": self._p,
                "is_test": not self.training,
                "dropout_implementation": self._impl,
            },
            ["Out"],
        )[0]


def _ntuple(v, n):
    """int-or-list spatial attr -> list of n ints (shared by the convs)."""
    return [v] * n if isinstance(v, int) else list(v)


def _conv_attrs(stride, padding, dilation, groups, n):
    return {
        "strides": _ntuple(stride, n),
        "paddings": _ntuple(padding, n),
        "dilations": _ntuple(dilation, n),
        "groups": groups or 1,
    }


def _bias_act(out, bias, act, axis=1):
    """Shared conv epilogue: channel bias + activation."""
    if bias is not None:
        out = _trace_op("elementwise_add", {"X": [out], "Y": [bias]},
                        {"axis": axis}, ["Out"])[0]
    if act:
        out = _trace_op(act, {"X": [out]}, {}, ["Out"])[0]
    return out


class Conv3D(Layer):
    """Reference dygraph/nn.py Conv3D over the conv3d op."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._attrs = _conv_attrs(stride, padding, dilation, groups, 3)
        self.weight = self.create_parameter(
            [num_filters, num_channels // (groups or 1)]
            + _ntuple(filter_size, 3),
            attr=ParamAttr._to_attr(param_attr))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        self._act = act

    def forward(self, x):
        out = _trace_op("conv3d", {"Input": [x], "Filter": [self.weight]},
                        self._attrs, ["Output"])[0]
        return _bias_act(out, self.bias, self._act)


class _ConvTransposeBase(Layer):
    """Shared machinery for Conv2DTranspose / Conv3DTranspose: output_size
    resolves to the op's output_padding (extra = requested - formula)."""

    _ndim = 2
    _op = "conv2d_transpose"

    def __init__(self, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1, groups=1,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        n = self._ndim
        self._attrs = _conv_attrs(stride, padding, dilation, groups, n)
        self._fs = _ntuple(filter_size, n)
        self._output_size = (None if output_size is None
                             else _ntuple(output_size, n))
        self.weight = self.create_parameter(
            [num_channels, num_filters // (groups or 1)] + self._fs,
            attr=ParamAttr._to_attr(param_attr))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        self._act = act

    def forward(self, x):
        attrs = dict(self._attrs)
        if self._output_size is not None:
            st, pd, dl = attrs["strides"], attrs["paddings"], attrs["dilations"]
            extra = []
            for i in range(self._ndim):
                formula = ((x.shape[2 + i] - 1) * st[i] - 2 * pd[i]
                           + dl[i] * (self._fs[i] - 1) + 1)
                e = self._output_size[i] - formula
                if e < 0 or e >= st[i]:
                    raise ValueError(
                        f"{type(self).__name__}: output_size "
                        f"{self._output_size[i]} unreachable from input "
                        f"{x.shape[2 + i]} (formula gives {formula}, "
                        f"stride {st[i]})")
                extra.append(e)
            attrs["output_padding"] = extra
        out = _trace_op(self._op, {"Input": [x], "Filter": [self.weight]},
                        attrs, ["Output"])[0]
        return _bias_act(out, self.bias, self._act)


class Conv2DTranspose(_ConvTransposeBase):
    _ndim = 2
    _op = "conv2d_transpose"


class Conv3DTranspose(_ConvTransposeBase):
    _ndim = 3
    _op = "conv3d_transpose"


class InstanceNorm(Layer):
    def __init__(self, num_channels, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._eps = epsilon
        self.scale = self.create_parameter(
            [num_channels], attr=ParamAttr._to_attr(param_attr),
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return _trace_op(
            "instance_norm",
            {"X": [x], "Scale": [self.scale], "Bias": [self.bias]},
            {"epsilon": self._eps}, ["Y"])[0]


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._attrs = {"groups": groups, "epsilon": epsilon}
        self.scale = self.create_parameter(
            [channels], attr=ParamAttr._to_attr(param_attr),
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(
            [channels], attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        self._act = act

    def forward(self, x):
        out = _trace_op(
            "group_norm",
            {"X": [x], "Scale": [self.scale], "Bias": [self.bias]},
            self._attrs, ["Y"])[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}
        h = weight_shape[dim]
        w = int(np.prod([s for i, s in enumerate(weight_shape) if i != dim]))
        self.weight_u = self.create_parameter([h])
        self.weight_v = self.create_parameter([w])

    def forward(self, weight):
        return _trace_op(
            "spectral_norm",
            {"Weight": [weight], "U": [self.weight_u], "V": [self.weight_v]},
            self._attrs, ["Out"])[0]


class PRelu(Layer):
    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            if channel is None:
                raise ValueError("PRelu(mode='channel') needs `channel`")
            shape = [channel]
        elif mode == "element":
            if input_shape is None:
                raise ValueError("PRelu(mode='element') needs `input_shape`")
            shape = list(input_shape)[1:]
        else:
            raise ValueError(f"PRelu: unknown mode {mode!r}")
        self.weight = self.create_parameter(
            shape, attr=ParamAttr._to_attr(param_attr),
            default_initializer=ConstantInitializer(0.25))

    def forward(self, x):
        return _trace_op("prelu", {"X": [x], "Alpha": [self.weight]},
                         {"mode": self._mode}, ["Out"])[0]


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim],
            attr=ParamAttr._to_attr(param_attr))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [1, output_dim], attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        self._act = act

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = _trace_op("bilinear_tensor_product", ins, {}, ["Out"])[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class GRUUnit(Layer):
    """Single GRU step (reference dygraph/nn.py GRUUnit / gru_unit_op.cc):
    input is the pre-projected [B, 3H] tensor (x @ W_x + b_x handled by
    the caller's fc), hidden [B, H]. The [H, 3H] weight splits into
    gate weights W_uz [H, 2H] and candidate weight W_c [H, H]:
      u, r = gate_act(x_ur + h @ W_uz);  c = act(x_c + (r*h) @ W_c)
      origin_mode=False (default): h' = (1-u)*h + u*c
      origin_mode=True:            h' = u*h + (1-u)*c
    """

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__(dtype=dtype)
        h = size // 3
        self._h = h
        self._origin_mode = origin_mode
        self.weight = self.create_parameter(
            [h, 3 * h], attr=ParamAttr._to_attr(param_attr))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [1, 3 * h], attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        self._act = activation
        self._gate_act = gate_activation

    def _slice(self, x, lo, hi):
        return _trace_op("slice", {"Input": [x]},
                         {"axes": [1], "starts": [lo], "ends": [hi]},
                         ["Out"])[0]

    def forward(self, input, hidden):
        h = self._h
        if self.bias is not None:
            input = _trace_op("elementwise_add",
                              {"X": [input], "Y": [self.bias]}, {},
                              ["Out"])[0]
        # reference gru_unit_op.h partitions the FLAT weight buffer (GEMM
        # ldb=2D): W_uh|W_rh = the first 2*H*H elements as [H, 2H], W_ch =
        # the last H*H as [H, H] — same layout as layers.gru_unit, so
        # checkpoints are interchangeable between the two APIs
        w_flat = _trace_op("reshape2", {"X": [self.weight]},
                           {"shape": [3 * h * h]}, ["Out", "XShape"])[0]
        w_uz = _trace_op("reshape2", {"X": [_trace_op(
            "slice", {"Input": [w_flat]},
            {"axes": [0], "starts": [0], "ends": [2 * h * h]}, ["Out"])[0]]},
            {"shape": [h, 2 * h]}, ["Out", "XShape"])[0]     # [H, 2H]
        w_c = _trace_op("reshape2", {"X": [_trace_op(
            "slice", {"Input": [w_flat]},
            {"axes": [0], "starts": [2 * h * h], "ends": [3 * h * h]},
            ["Out"])[0]]}, {"shape": [h, h]}, ["Out", "XShape"])[0]  # [H, H]
        h_uz = _trace_op("matmul", {"X": [hidden], "Y": [w_uz]}, {},
                         ["Out"])[0]
        gates = _trace_op(self._gate_act, {"X": [_trace_op(
            "elementwise_add",
            {"X": [self._slice(input, 0, 2 * h)], "Y": [h_uz]}, {},
            ["Out"])[0]]}, {}, ["Out"])[0]
        u = self._slice(gates, 0, h)
        r = self._slice(gates, h, 2 * h)
        rh = _trace_op("elementwise_mul", {"X": [r], "Y": [hidden]}, {},
                       ["Out"])[0]
        rh_c = _trace_op("matmul", {"X": [rh], "Y": [w_c]}, {}, ["Out"])[0]
        c = _trace_op(self._act, {"X": [_trace_op(
            "elementwise_add",
            {"X": [self._slice(input, 2 * h, 3 * h)], "Y": [rh_c]}, {},
            ["Out"])[0]]}, {}, ["Out"])[0]
        one_minus_u = _trace_op("scale", {"X": [u]},
                                {"scale": -1.0, "bias": 1.0}, ["Out"])[0]
        if self._origin_mode:
            keep, take = u, one_minus_u
        else:
            keep, take = one_minus_u, u
        new_h = _trace_op("elementwise_add", {"X": [_trace_op(
            "elementwise_mul", {"X": [keep], "Y": [hidden]}, {}, ["Out"])[0]],
            "Y": [_trace_op("elementwise_mul", {"X": [take], "Y": [c]}, {},
                            ["Out"])[0]]}, {}, ["Out"])[0]
        gate = _trace_op("concat", {"X": [u, r, c]}, {"axis": 1}, ["Out"])[0]
        return new_h, rh, gate


class NCE(Layer):
    """Dygraph NCE head (reference dygraph/nn.py NCE) over the same
    composition the static layers.nce uses."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", seed=0, is_sparse=False, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_total_classes, dim], attr=ParamAttr._to_attr(param_attr))
        self.bias = self.create_parameter(
            [num_total_classes], attr=ParamAttr._to_attr(bias_attr),
            is_bias=True)
        self._c = num_total_classes
        self._k = num_neg_samples
        self._seed = seed

    def forward(self, input, label):
        b = input.shape[0]
        lbl = _trace_op("reshape", {"X": [label]}, {"shape": [b]}, ["Out"])[0]
        w_pos = _trace_op("gather", {"X": [self.weight], "Index": [lbl]},
                          {}, ["Out"])[0]
        b2 = _trace_op("reshape", {"X": [self.bias]},
                       {"shape": [self._c, 1]}, ["Out"])[0]
        b_pos = _trace_op("reshape", {"X": [_trace_op(
            "gather", {"X": [b2], "Index": [lbl]}, {}, ["Out"])[0]]},
            {"shape": [b, 1]}, ["Out"])[0]
        s_pos = _trace_op("elementwise_add", {"X": [_trace_op(
            "reduce_sum", {"X": [_trace_op(
                "elementwise_mul", {"X": [input], "Y": [w_pos]}, {},
                ["Out"])[0]]},
            {"dim": [-1], "keep_dim": True}, ["Out"])[0]], "Y": [b_pos]},
            {}, ["Out"])[0]
        # uniform in [0, C): int cast covers every class 0..C-1
        neg = _trace_op("uniform_random", {},
                        {"shape": [self._k], "min": 0.0,
                         "max": float(self._c), "dtype": "float32",
                         "seed": self._seed}, ["Out"])[0]
        neg_ids = _trace_op("cast", {"X": [neg]}, {"out_dtype": "int64"},
                            ["Out"])[0]
        w_neg = _trace_op("gather", {"X": [self.weight], "Index": [neg_ids]},
                          {}, ["Out"])[0]
        b_neg = _trace_op("reshape", {"X": [_trace_op(
            "gather", {"X": [b2], "Index": [neg_ids]}, {}, ["Out"])[0]]},
            {"shape": [1, self._k]}, ["Out"])[0]
        s_neg = _trace_op("elementwise_add", {"X": [_trace_op(
            "matmul", {"X": [input], "Y": [w_neg]},
            {"transpose_Y": True}, ["Out"])[0]], "Y": [b_neg]}, {},
            ["Out"])[0]
        pos_term = _trace_op("softplus", {"X": [_trace_op(
            "scale", {"X": [s_pos]}, {"scale": -1.0}, ["Out"])[0]]}, {},
            ["Out"])[0]
        neg_term = _trace_op("reduce_sum", {"X": [_trace_op(
            "softplus", {"X": [s_neg]}, {}, ["Out"])[0]]},
            {"dim": [-1], "keep_dim": True}, ["Out"])[0]
        return _trace_op("elementwise_add",
                         {"X": [pos_term], "Y": [neg_term]}, {}, ["Out"])[0]


class SequenceConv(Layer):
    def __init__(self, name_scope=None, num_filters=1, filter_size=3,
                 filter_stride=1, padding=True, bias_attr=None,
                 param_attr=None, act=None, dtype="float32", input_dim=None):
        super().__init__(dtype=dtype)
        if input_dim is None:
            raise ValueError("SequenceConv needs input_dim on TPU "
                             "(static parameter shapes)")
        self._attrs = {"contextLength": filter_size, "contextStride": filter_stride,
                       "contextStart": -(filter_size // 2)}
        self.weight = self.create_parameter(
            [filter_size * input_dim, num_filters],
            attr=ParamAttr._to_attr(param_attr))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        self._act = act

    def forward(self, x):
        out = _trace_op("sequence_conv",
                        {"X": [x], "Filter": [self.weight]},
                        self._attrs, ["Out"])[0]
        return _bias_act(out, self.bias, self._act, axis=-1)


class RowConv(Layer):
    def __init__(self, name_scope=None, future_context_size=2,
                 param_attr=None, act=None, dtype="float32", input_dim=None):
        super().__init__(dtype=dtype)
        if input_dim is None:
            raise ValueError("RowConv needs input_dim on TPU")
        self.weight = self.create_parameter(
            [future_context_size + 1, input_dim],
            attr=ParamAttr._to_attr(param_attr))
        self._act = act

    def forward(self, x):
        out = _trace_op("row_conv", {"X": [x], "Filter": [self.weight]},
                        {}, ["Out"])[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class TreeConv(Layer):
    """Tree-based convolution (reference dygraph/nn.py TreeConv over
    tree_conv_op.cc): patch structure from EdgeSet host-side, learnable
    einsum on device (ops/misc_ops.py tree_conv)."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__()
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters],
            attr=ParamAttr._to_attr(param_attr))
        # sibling-layer convention: None -> default bias, False -> none
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_filters], attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, nodes_vector, edge_set):
        out = _trace_op(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self.weight]},
            {"max_depth": self._max_depth}, ["Out"])[0]
        if self.bias is not None:
            out = _trace_op("elementwise_add", {"X": [out], "Y": [self.bias]},
                            {"axis": 3}, ["Out"])[0]
        if self._act:
            out = _trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Sequential(Layer):
    """Ordered container (reference dygraph/container.py Sequential)."""

    def __init__(self, *layers_):
        super().__init__()
        self._seq = []
        for i, item in enumerate(layers_):
            if isinstance(item, (list, tuple)):
                name, layer = item
            else:
                name, layer = str(i), item
            setattr(self, f"_seq_{name}", layer)  # registers as sublayer
            self._seq.append(layer)

    def forward(self, x):
        for layer in self._seq:
            x = layer(x)
        return x

    def __getitem__(self, i):
        return self._seq[i]

    def __len__(self):
        return len(self._seq)


class LayerList(Layer):
    """Indexable list of sublayers (reference container.py LayerList)."""

    def __init__(self, sublayers=None):
        super().__init__()
        self._list = []
        for layer in sublayers or []:
            self.append(layer)

    def append(self, layer):
        setattr(self, f"_ll_{len(self._list)}", layer)
        self._list.append(layer)
        return self

    def __getitem__(self, i):
        return self._list[i]

    def __iter__(self):
        return iter(self._list)

    def __len__(self):
        return len(self._list)


class ParameterList(Layer):
    """Indexable list of parameters (reference container.py)."""

    def __init__(self, parameters=None):
        super().__init__()
        self._plist = []
        for p in parameters or []:
            self.append(p)

    def append(self, p):
        self._plist.append(p)
        self._parameters[f"_pl_{len(self._plist) - 1}"] = p
        return self

    def __getitem__(self, i):
        return self._plist[i]

    def __iter__(self):
        return iter(self._plist)

    def __len__(self):
        return len(self._plist)
