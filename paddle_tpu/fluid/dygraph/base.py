"""Dygraph core: VarBase, Tracer (eager execution + tape autograd).

Parity surface: /root/reference/paddle/fluid/imperative/
(Tracer::TraceOp tracer.cc:45, VarBase layer.h:56, BasicEngine::Execute
basic_engine.cc:161, GradientAccumulator).

TPU-native design: eager mode IS jax eager — each traced op calls the
same registered emitter the static executor uses, so kernels are
per-op-jitted by jax with its own caching. The tape records
(op, in VarBases, out VarBases, attrs); backward() is a reverse tape walk
calling the SAME grad emitters as static append_backward, accumulating
into VarBase.grad (the GradientAccumulator role). No separate kernel
library and no separate autodiff.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from .. import framework, unique_name
from ...ops import registry

GRAD = "@GRAD"


class VarBase:
    """Eager tensor (reference imperative/layer.h:56)."""

    def __init__(
        self,
        value=None,
        name: Optional[str] = None,
        stop_gradient: bool = False,
        persistable: bool = False,
    ):
        import jax.numpy as jnp

        self.value = None if value is None else jnp.asarray(value)
        self.name = name or unique_name.generate("eager_tmp")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad: Optional[Any] = None

    # -- introspection --------------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape) if self.value is not None else None

    @property
    def dtype(self):
        return self.value.dtype if self.value is not None else None

    def numpy(self):
        return np.asarray(self.value)

    @property
    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    def astype(self, dtype):
        return _trace_op("cast", {"X": [self]}, {"out_dtype": dtype}, ["Out"])[0]

    def backward(self, retain_graph: bool = False):
        tracer = framework._dygraph_tracer()
        if tracer is None:
            raise RuntimeError("VarBase.backward() outside dygraph guard")
        tracer.run_backward(self, retain_graph=retain_graph)

    def __repr__(self):
        return f"VarBase(name={self.name}, shape={self.shape}, stop_gradient={self.stop_gradient})\n{self.value}"

    # -- arithmetic sugar ------------------------------------------------
    def _binary(self, other, op, reverse=False):
        if not isinstance(other, VarBase):
            other = VarBase(np.asarray(other, dtype=np.asarray(self.value).dtype), stop_gradient=True)
        x, y = (other, self) if reverse else (self, other)
        return _trace_op(op, {"X": [x], "Y": [y]}, {}, ["Out"])[0]

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __matmul__(self, o):
        return _trace_op("matmul_v2", {"X": [self], "Y": [o]}, {}, ["Out"])[0]

    def __neg__(self):
        return _trace_op("scale", {"X": [self]}, {"scale": -1.0}, ["Out"])[0]

    # -- reduction/reshape sugar (reference varbase_patch_methods.py) ----
    def mean(self, axis=None, keepdim=False):
        attrs = {"reduce_all": axis is None, "keep_dim": keepdim}
        if axis is not None:
            attrs["dim"] = [axis] if isinstance(axis, int) else list(axis)
        return _trace_op("reduce_mean", {"X": [self]}, attrs, ["Out"])[0]

    def sum(self, axis=None, keepdim=False):
        attrs = {"reduce_all": axis is None, "keep_dim": keepdim}
        if axis is not None:
            attrs["dim"] = [axis] if isinstance(axis, int) else list(axis)
        return _trace_op("reduce_sum", {"X": [self]}, attrs, ["Out"])[0]

    def reshape(self, shape):
        return _trace_op("reshape", {"X": [self]},
                         {"shape": list(shape)}, ["Out"])[0]

    def transpose(self, perm):
        return _trace_op("transpose", {"X": [self]},
                         {"axis": list(perm)}, ["Out"])[0]


class Tracer:
    """Eager executor + tape recorder (reference imperative/tracer.cc:45)."""

    def __init__(self):
        self.tape: List[tuple] = []
        self._no_grad_depth = 0
        self._rng_key = None
        self.train_mode = True

    def _ctx(self):
        import jax

        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(0)
        self._rng_key, sub = jax.random.split(self._rng_key)
        return registry.EmitContext(rng_key=sub)

    @property
    def grad_enabled(self) -> bool:
        return self._no_grad_depth == 0

    def trace_op(
        self,
        type: str,
        inputs: Dict[str, List[VarBase]],
        attrs: Dict[str, Any],
        out_slots: List[str],
    ) -> Dict[str, List[VarBase]]:
        spec = registry.get(type)
        if spec is None:
            raise KeyError(f"op {type!r} has no registered emitter")
        ins_vals = {
            slot: [v.value for v in vs] for slot, vs in inputs.items() if vs
        }
        outs_vals = spec.emit(self._ctx(), ins_vals, dict(attrs))
        outputs: Dict[str, List[VarBase]] = {}
        for slot in outs_vals if out_slots is None else out_slots:
            vals = outs_vals.get(slot)
            if vals is None:
                continue
            outputs[slot] = [VarBase(v) for v in vals]
        requires = self.grad_enabled and any(
            not v.stop_gradient for vs in inputs.values() for v in vs
        ) and not spec.stop_gradient
        if requires:
            self.tape.append((type, dict(inputs), dict(outputs), dict(attrs)))
        else:
            for vs in outputs.values():
                for v in vs:
                    v.stop_gradient = True
        return outputs

    # -- autograd (reference BasicEngine::Execute) -----------------------
    def run_backward(self, root: VarBase, retain_graph: bool = False):
        import jax.numpy as jnp

        grads: Dict[int, Any] = {id(root): jnp.ones_like(root.value)}
        holders: Dict[int, VarBase] = {id(root): root}

        for type, inputs, outputs, attrs in reversed(self.tape):
            out_grads: Dict[str, List[Optional[Any]]] = {}
            any_grad = False
            for slot, vs in outputs.items():
                gs = [grads.get(id(v)) for v in vs]
                if any(g is not None for g in gs):
                    any_grad = True
                out_grads[slot] = gs
            if not any_grad:
                continue

            spec = registry.get(type)
            gspec = registry.get(type + "_grad")
            if gspec is None:
                raise NotImplementedError(f"op {type!r} has no gradient path")

            # assemble grad-emitter inputs: fwd ins + fwd outs + out grads
            gins: Dict[str, List[Any]] = {}
            for slot, vs in inputs.items():
                gins[slot] = [v.value for v in vs]
            for slot, vs in outputs.items():
                gins.setdefault(slot, [v.value for v in vs])
            for slot, gs in out_grads.items():
                filled = []
                for g, v in zip(gs, outputs[slot]):
                    filled.append(
                        g if g is not None else jnp.zeros_like(v.value)
                    )
                gins[slot + GRAD] = filled

            gattrs = dict(attrs)
            gattrs["__fwd_in_slots__"] = list(inputs.keys())
            gouts = gspec.emit(self._ctx(), gins, gattrs)

            for slot, vs in inputs.items():
                gvals = gouts.get(slot + GRAD)
                if gvals is None:
                    continue
                for v, g in zip(vs, gvals):
                    if v.stop_gradient or g is None:
                        continue
                    cur = grads.get(id(v))
                    grads[id(v)] = g if cur is None else cur + g
                    holders[id(v)] = v

        for vid, g in grads.items():
            v = holders[vid]
            if v.stop_gradient:
                continue
            v.grad = g if v.grad is None else v.grad + g
        if not retain_graph:
            self.tape.clear()


def _trace_op(type, inputs, attrs, out_slots):
    tracer = framework._dygraph_tracer()
    outs = tracer.trace_op(type, inputs, attrs, out_slots)
    flat = [v for slot in out_slots for v in outs.get(slot, [])]
    return flat


# ---------------------------------------------------------------------------
# mode guards (reference dygraph/base.py)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def guard(place=None):
    tracer = Tracer()
    old = framework._dygraph_tracer_
    framework._dygraph_tracer_ = tracer
    try:
        yield
    finally:
        framework._dygraph_tracer_ = old


def enabled() -> bool:
    return framework.in_dygraph_mode()


@contextlib.contextmanager
def no_grad_ctx():
    tracer = framework._dygraph_tracer()
    if tracer is None:
        yield
        return
    tracer._no_grad_depth += 1
    try:
        yield
    finally:
        tracer._no_grad_depth -= 1


def no_grad(fn=None):
    """Decorator or context manager."""
    if fn is None:
        return no_grad_ctx()
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad_ctx():
            return fn(*a, **k)

    return wrapper


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name)
