"""AST-based dygraph-to-static conversion (data-dependent control flow).

Parity surface: reference
python/paddle/fluid/dygraph/dygraph_to_static/ast_transformer.py +
program_translator.py:348 — Python `if`/`while`/`for range()` whose
condition is a TENSOR become cond / while_loop ops, which a tracer alone
cannot capture (it would bake in the branch taken by the example input).

Design: a source-to-source rewrite with RUNTIME dispatch, the reference's
convert_ifelse/convert_while_loop scheme. Each `if`/`while` is rewritten
into nested functions over its carried names (the names assigned inside)
plus a `_jst_if`/`_jst_while` call:

    if pred: A            _t(c1..):  A;  return (c1..)
    else:    B     ->     _f(c1..):  B;  return (c1..)
                          (c1..) = _jst_if(pred, _t, _f, (c1..))

At runtime, a plain Python bool takes the normal branch; a static
`framework.Variable` (what flows through a to_static trace) builds
layers.cond / layers.while_loop, so BOTH branches / the loop body are
traced symbolically and the choice happens on-device.

Supported subset (documented, reference-style): `if`/`while` whose
bodies have no `return`/`break`/`continue` (such nodes are left
untransformed and keep trace semantics), and `for <name> in range(...)`
(desugared to a while). Carried names must be assignable tensors in the
tensor-predicate case; names undefined on entry ride an UNDEF sentinel
that raises only if actually used.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Any, Callable, Dict

__all__ = [
    "ast_to_static", "convert_ifelse", "convert_while", "ConversionError",
]


class ConversionError(RuntimeError):
    pass


class _Undef:
    """Sentinel for 'name not bound on entry' — raises only when used."""

    _inst = None

    def __repr__(self):
        return "<undefined local (dygraph_to_static)>"

    def _raise(self, *_a, **_k):
        raise ConversionError(
            "a name assigned inside a converted tensor-condition branch "
            "was read before being defined on every path"
        )

    __call__ = __add__ = __radd__ = __mul__ = __getattr__ = _raise


_UNDEF = _Undef()


def _is_static_var(x) -> bool:
    from ... import framework

    return isinstance(x, framework.Variable)


def convert_ifelse(pred, true_fn, false_fn, args):
    """Runtime dispatch for a rewritten `if` (reference
    convert_operators.convert_ifelse)."""
    if _is_static_var(pred):
        from ...layers import control_flow

        def _checked(fn):
            # entry values may be UNDEF (name first assigned inside the
            # branch); what each branch RETURNS must be real tensors, or
            # cond cannot match the true/false structures
            def run():
                import numbers

                from ...layers import tensor as _tensor

                out = list(fn(*args))
                lifted = []
                for o in out:
                    if o is _UNDEF:
                        raise ConversionError(
                            "tensor-condition `if`: every name assigned "
                            "in one branch must be assigned in the other "
                            "(or defined before the `if`) — cond needs "
                            "matching true/false structures"
                        )
                    if not _is_static_var(o):
                        # python-number carried values lift to constant
                        # tensors, matching convert_while (ADVICE r3)
                        if not isinstance(o, numbers.Number):
                            raise ConversionError(
                                "tensor-condition `if`: branch-carried "
                                "values must be tensors or numbers, got "
                                f"{type(o).__name__}"
                            )
                        o = _tensor.fill_constant(
                            [1], "int32" if isinstance(o, int) else "float32",
                            o,
                        )
                    lifted.append(o)
                return lifted

            return run

        out = control_flow.cond(pred, _checked(true_fn), _checked(false_fn))
        return tuple(out)
    return true_fn(*args) if pred else false_fn(*args)


def convert_while(cond_fn, body_fn, args):
    """Runtime dispatch for a rewritten `while` (reference
    convert_operators.convert_while_loop)."""
    pred0 = cond_fn(*args)
    if _is_static_var(pred0):
        from ...layers import control_flow, tensor as _tensor

        loop_vars = []
        for a in args:
            if a is _UNDEF:
                raise ConversionError(
                    "tensor-condition `while`: every carried name must be "
                    "defined before the loop"
                )
            if not _is_static_var(a):
                # python-number carried state (e.g. the desugared
                # for-range counter) lifts to a constant tensor
                import numbers

                if not isinstance(a, numbers.Number):
                    raise ConversionError(
                        "tensor-condition `while`: carried values must be "
                        f"tensors or numbers, got {type(a).__name__}"
                    )
                a = _tensor.fill_constant(
                    [1], "int32" if isinstance(a, int) else "float32", a
                )
            loop_vars.append(a)
        out = control_flow.while_loop(
            lambda *vs: cond_fn(*vs), lambda *vs: list(body_fn(*vs)),
            loop_vars,
        )
        return tuple(out)
    while pred0:
        args = tuple(body_fn(*args))
        pred0 = cond_fn(*args)
    return tuple(args)


def _maybe(name: str) -> str:
    # read a possibly-unbound local: UnboundLocalError/NameError -> UNDEF
    return (
        f"_jst_get(lambda: {name})"
    )


def _jst_get(thunk):
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return _UNDEF


def _jst_eq(a, b):
    if _is_static_var(a):
        return a._binary(b, "equal")  # lifts python scalars
    if _is_static_var(b):
        return b._binary(a, "equal")
    return a == b


def _jst_ne(a, b):
    if _is_static_var(a):
        return a._binary(b, "not_equal")
    if _is_static_var(b):
        return b._binary(a, "not_equal")
    return a != b


def _assigned_names(stmts) -> set:
    """Names (re)bound anywhere inside `stmts` — the carried state."""
    out = set()

    class V(ast.NodeVisitor):
        def _target(self, t):
            if isinstance(t, ast.Name):
                if not t.id.startswith("_jst"):
                    out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)

        def visit_Assign(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            if node.value is not None:
                self._target(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            if not node.name.startswith("_jst"):
                out.add(node.name)  # nested defs rebind their name

    for s in stmts:
        V().visit(s)
    return out


def _has_flow_escape(stmts) -> bool:
    """return/break/continue at this statement level (not inside nested
    function definitions) — such nodes keep trace semantics."""
    found = False

    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            nonlocal found
            found = True

        def visit_Break(self, node):
            nonlocal found
            found = True

        def visit_Continue(self, node):
            nonlocal found
            found = True

        def visit_FunctionDef(self, node):
            pass  # different scope

        def visit_Lambda(self, node):
            pass

    for s in stmts:
        V().visit(s)
    return found


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If / While / For-range into _jst_if/_jst_while calls."""

    def __init__(self):
        self._n = 0

    def _fresh(self, base):
        self._n += 1
        return f"_jst_{base}_{self._n}"

    def _carried(self, *stmt_lists):
        names = set()
        for sl in stmt_lists:
            names |= _assigned_names(sl)
        return sorted(names)

    def _stmt(self, src: str):
        return ast.parse(textwrap.dedent(src)).body[0]

    def _make_fn(self, name, params, body, result_names):
        src = f"def {name}({', '.join(params)}):\n    pass"
        fn = self._stmt(src)
        ret = self._stmt(f"return ({', '.join(result_names)},)" if result_names
                         else "return ()")
        fn.body = body + [ret]
        return fn

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            return node
        carried = self._carried(node.body, node.orelse)
        if not carried:
            return node
        tname, fname = self._fresh("true"), self._fresh("false")
        t_fn = self._make_fn(tname, carried, node.body, carried)
        f_fn = self._make_fn(
            fname, carried, node.orelse or [ast.Pass()], carried
        )
        cur = ", ".join(_maybe(n) for n in carried)
        call = self._stmt(
            f"({', '.join(carried)},) = _jst_if(_jst_pred, {tname}, "
            f"{fname}, ({cur},))"
        )
        # splice the original test expression in for _jst_pred
        class Sub(ast.NodeTransformer):
            def visit_Name(self, n):
                if n.id == "_jst_pred":
                    return node.test
                return n

        call = Sub().visit(call)
        # python-bool path: a name assigned in only one branch comes back
        # as the _UNDEF sentinel — unbind it so later reads raise the
        # normal UnboundLocalError instead of leaking the sentinel into
        # identity checks / repr / pass-through (ADVICE r3). The tensor
        # path never returns _UNDEF (convert_ifelse raises first).
        cleanup = [
            self._stmt(f"if {n} is _jst_UNDEF:\n    del {n}")
            for n in carried
        ]
        return [t_fn, f_fn, call] + cleanup

    def visit_While(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or node.orelse:
            return node
        carried = self._carried(node.body, [ast.Expr(node.test)])
        # the test's read names that are assigned in the body are already
        # carried; add names READ by the test that the body rebinds is
        # covered; carry also test-only names that are plain locals? no:
        # loop-invariant reads ride the closure.
        if not carried:
            return node
        cname, bname = self._fresh("cond"), self._fresh("body")
        c_fn = self._make_fn(cname, carried, [], [])
        c_fn.body = [ast.Return(node.test)]
        b_fn = self._make_fn(bname, carried, node.body, carried)
        cur = ", ".join(_maybe(n) for n in carried)
        call = self._stmt(
            f"({', '.join(carried)},) = _jst_while({cname}, {bname}, "
            f"({cur},))"
        )
        return [c_fn, b_fn, call]

    def visit_For(self, node):
        self.generic_visit(node)
        step_lit = 1
        if (
            not isinstance(node.target, ast.Name)
            or not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or node.iter.keywords
            or not 1 <= len(node.iter.args) <= 3
        ):
            return node
        if len(node.iter.args) == 3:
            # the loop direction must be known at transform time: only a
            # literal step is accepted (a symbolic one would silently run
            # `<` against a descending range)
            s = node.iter.args[2]
            if (
                isinstance(s, ast.Constant) and isinstance(s.value, int)
                and s.value != 0
            ):
                step_lit = s.value
            elif (
                isinstance(s, ast.UnaryOp) and isinstance(s.op, ast.USub)
                and isinstance(s.operand, ast.Constant)
                and isinstance(s.operand.value, int) and s.operand.value != 0
            ):
                step_lit = -s.operand.value
            else:
                return node
        if _has_flow_escape(node.body) or node.orelse:
            return node
        i = node.target.id
        a = node.iter.args
        sv, ev = self._fresh("start"), self._fresh("stop")
        pre = []
        if len(a) == 1:
            pre.append(self._stmt(f"{sv} = 0"))
            pre.append(ast.Assign([ast.Name(ev, ast.Store())], a[0]))
        else:
            pre.append(ast.Assign([ast.Name(sv, ast.Store())], a[0]))
            pre.append(ast.Assign([ast.Name(ev, ast.Store())], a[1]))
        # pre-increment form: i enters at start-step and steps FIRST, so
        # after the loop i holds the LAST iteration's value (Python's
        # post-loop binding), not one-past-the-end
        pre.append(self._stmt(f"{i} = {sv} - ({step_lit})"))
        body = [self._stmt(f"{i} = {i} + ({step_lit})")] + list(node.body)
        cmp = "<" if step_lit > 0 else ">"
        wh = ast.While(
            test=ast.parse(f"({i} + ({step_lit})) {cmp} ({ev} + 0)",
                           mode="eval").body,
            body=body, orelse=[],
        )
        out = self.visit_While(wh)
        return pre + (out if isinstance(out, list) else [out])

    def visit_Compare(self, node):
        self.generic_visit(node)
        # `a == b` / `a != b` on tensors must emit equal/not_equal ops,
        # but patching Variable.__eq__ globally would corrupt identity
        # checks and `in` membership across the codebase — so the rewrite
        # is scoped to converted functions via a runtime helper
        if len(node.ops) != 1 or not isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            return node
        fn = "_jst_eq" if isinstance(node.ops[0], ast.Eq) else "_jst_ne"
        return ast.Call(
            func=ast.Name(fn, ast.Load()),
            args=[node.left, node.comparators[0]], keywords=[],
        )


_converted: Dict[Any, Callable] = {}


def ast_to_static(fn: Callable) -> Callable:
    """Rewrite `fn`'s data-dependent control flow; returns the converted
    function (or `fn` itself when the source is unavailable)."""
    if fn in _converted:
        return _converted[fn]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
        fndef = tree.body[0]
        fndef.decorator_list = []
        new_body = []
        tr = _ControlFlowTransformer()
        for s in fndef.body:
            r = tr.visit(s)
            new_body.extend(r if isinstance(r, list) else [r])
        fndef.body = new_body
        ast.fix_missing_locations(tree)
        code = compile(tree, f"<to_static {fn.__qualname__}>", "exec")
    except ConversionError:
        raise
    except Exception:  # noqa: BLE001 — unparseable constructs: trace as-is
        return fn
    helpers = {
        "_jst_if": convert_ifelse,
        "_jst_while": convert_while,
        "_jst_get": _jst_get,
        "_jst_UNDEF": _UNDEF,
        "_jst_eq": _jst_eq,
        "_jst_ne": _jst_ne,
    }
    if fn.__closure__:
        # closures force a by-value globals snapshot (cells cannot be
        # reattached to recompiled code); closure-free functions — module
        # functions and methods, the common case — exec against the LIVE
        # module globals so later rebinding stays visible, with only the
        # collision-safe _jst_* helper names added
        glb = dict(fn.__globals__)
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
        glb.update(helpers)
        exec(code, glb)  # noqa: S102 — our own transformed source
        out = glb[fn.__name__]
    else:
        fn.__globals__.update(helpers)
        sentinel = object()
        prev = fn.__globals__.get(fn.__name__, sentinel)
        exec(code, fn.__globals__)  # noqa: S102
        out = fn.__globals__[fn.__name__]
        if prev is sentinel:
            del fn.__globals__[fn.__name__]
        else:
            fn.__globals__[fn.__name__] = prev
    out.__wrapped__ = fn
    _converted[fn] = out
    return out
