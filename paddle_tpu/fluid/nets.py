"""Composite network blocks (reference python/paddle/fluid/nets.py):
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention — pure layer compositions; XLA fuses them."""
from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "sequence_conv_pool", "glu",
           "scaled_dot_product_attention", "img_conv_group"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Stacked conv(+bn+dropout) layers followed by one pool (VGG block)."""
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]

    n = len(conv_num_filter)

    def per_layer(v, n_=n):
        if isinstance(v, (list, tuple)):
            if len(v) != n_:
                raise ValueError(
                    f"img_conv_group: per-layer list {list(v)} must have "
                    f"len(conv_num_filter) == {n_} entries")
            return list(v)
        return [v] * n_
    paddings = per_layer(conv_padding)
    filter_sizes = per_layer(conv_filter_size)
    with_bn = per_layer(conv_with_batchnorm)
    drop_rates = per_layer(conv_batchnorm_drop_rate)
    attrs = per_layer(param_attr) if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * n

    tmp = input
    for i in range(n):
        tmp = layers.conv2d(
            tmp, num_filters=conv_num_filter[i],
            filter_size=filter_sizes[i], padding=paddings[i],
            param_attr=attrs[i],
            act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if drop_rates[i] > 0:
                tmp = layers.dropout(tmp, dropout_prob=drop_rates[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       length=None):
    conv_out = layers.sequence_conv(
        input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.sequence_pool(conv_out, pool_type=pool_type, length=length)


def glu(input, dim=-1):
    """Gated linear unit: split in two along dim, a * sigmoid(b)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled-dot-product attention over [B, S, H] tensors —
    delegates to the fused op (Pallas flash attention on TPU)."""
    return layers.fused_multihead_attention(
        queries, keys, values, num_heads=num_heads,
        dropout_prob=dropout_rate, is_test=dropout_rate == 0.0)
