"""Executor: runs a Program by JIT-compiling whole blocks via XLA.

Parity surface: reference Executor (python/paddle/fluid/executor.py:896,
paddle/fluid/framework/executor.cc:180) and Scope
(paddle/fluid/framework/scope.h:46).

TPU-native design — the central departure from the reference:
the reference interprets a block op-by-op (executor.cc:465-471), paying
per-op dispatch; here the whole block is traced once into a single JAX
function and compiled by XLA, so op boundaries vanish (fusion) and the
train step — forward, backward, optimizer update — is ONE device program.
Scope state (parameters, optimizer moments, RNG key) is threaded through
the compiled function functionally and donated, so parameters are updated
in-place in device memory. The compile cache is keyed on
(program identity+version, feed signature, fetch names), mirroring the
reference's `Executor._prepare` program cache.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import framework, monitor
from .dtypes import convert_dtype
from .profiler import RecordEvent
from ..ops import registry
from ..telemetry import numerics as _numerics
from ..telemetry import tracing as _tracing


class Scope:
    """name -> device array holder (reference scope.h:46, flat here: XLA
    owns the memory; hierarchy is unnecessary without per-op locals)."""

    def __init__(self):
        self.vars: Dict[str, Any] = {}
        self._rng_key = None

    def find_var(self, name: str):
        return self.vars.get(name)

    def var(self, name: str):
        return self.vars.setdefault(name, None)

    def set_var(self, name: str, value):
        self.vars[name] = value

    def drop_kids(self):
        self.vars.clear()


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return _guard()


class _CompiledBlock:
    def __init__(self, fn, feed_names, donate_names, keep_names, state_out_names, fetch_names):
        self.fn = fn
        self.feed_names = feed_names
        # scope vars read AND overwritten -> donated to XLA (in-place update)
        self.donate_names = donate_names
        # scope vars only read -> must survive the call
        self.keep_names = keep_names
        self.state_out_names = state_out_names
        self.fetch_names = fetch_names
        # name -> NamedSharding when compiled over a mesh (else empty):
        # scope arrays produced by an unsharded startup run are resharded
        # on first use (device_put is a no-op when already placed right)
        self.state_shardings: Dict[str, Any] = {}


class Executor:
    """place is accepted for API parity; JAX owns device placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, _CompiledBlock] = {}

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[framework.Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,  # parity arg; always cached
    ):
        # step telemetry (fluid/monitor.py): rec is None unless
        # PADDLE_METRICS_PATH armed the JSONL sink — the flag-off hot
        # path pays one attribute read here and nothing below. The step
        # span (PADDLE_TRACING) is the ROOT of this step's causal trace:
        # data-wait/compile/device/fetch children below, plus every PS
        # RPC the step issues from this thread, share its trace_id, and
        # the kind="step" record carries it (tracetop joins on it).
        rec = monitor.begin_step()
        with _tracing.step_span():
            try:
                out = self._run_impl(program, feed, fetch_list, scope,
                                     return_numpy, rec)
            except BaseException as exc:
                monitor.abandon_step()
                try:
                    # goodput ledger (ISSUE 15): an un-committed step's
                    # window is badput — BadStepError means discarded
                    # work (bad_step_replay), anything else a stall
                    from ..telemetry import goodput as _goodput

                    _goodput.on_abandoned_step(
                        type(exc).__name__ == "BadStepError")
                except Exception:  # noqa: BLE001 — accounting only
                    pass
                raise
        monitor.commit_step(rec)
        return out

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy,
                  rec):
        import time as _time

        if program is None:
            program = framework.default_main_program()
        # CompiledProgram wrapper (compiler.py) delegates here
        if hasattr(program, "_program"):
            program = program._program
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()

        fetch_names = tuple(
            v.name if isinstance(v, framework.Variable) else str(v) for v in fetch_list
        )
        block = program.global_block()

        t_feed = _time.perf_counter() if rec is not None else 0.0
        with _tracing.span("data_wait"):
            feed_arrays = self._prepare_feed(block, feed)
        if rec is not None:
            rec.data_wait_ms += (_time.perf_counter() - t_feed) * 1e3
        from .flags import flag

        # the nan/inf debugging mode and the bad-step guard both disable
        # buffer donation (donated buffers are destroyed by the step,
        # which would make "recover / keep the last good parameters"
        # impossible), so the compile cache must distinguish the modes
        check_nan = flag("FLAGS_check_nan_inf")
        check_numerics = flag("FLAGS_check_numerics")
        compiled = self._ensure_compiled(
            program, block, feed_arrays, fetch_names, scope,
            check_nan or check_numerics,
        )
        self._ensure_rng(scope, program)

        def _load(names):
            d = {}
            for n in names:
                v = scope.find_var(n)
                if v is None:
                    raise RuntimeError(
                        f"Variable {n!r} is used before initialization; "
                        f"run the startup program first."
                    )
                target = compiled.state_shardings.get(n)
                if target is not None and getattr(v, "sharding", None) != target:
                    import jax

                    v = jax.device_put(v, target)
                d[n] = v
            return d

        donated = _load(compiled.donate_names)
        kept = _load(compiled.keep_names)
        if getattr(compiled, "repl_sharding", None) is not None:
            import jax

            if jax.process_count() > 1:
                # multi-process jit rejects host numpy for sharded params:
                # build global jax.Arrays from the (identical-per-process)
                # full batch; each process materializes only its shards
                feed_arrays = {
                    n: (
                        a if isinstance(a, jax.Array)
                        else jax.make_array_from_callback(
                            np.shape(a), compiled.feed_shardings[n],
                            lambda idx, a=a: np.asarray(a)[idx],
                        )
                    )
                    for n, a in feed_arrays.items()
                }
                if getattr(scope._rng_key, "sharding", None) != compiled.repl_sharding:
                    scope._rng_key = jax.device_put(
                        scope._rng_key, compiled.repl_sharding
                    )
        bench = flag("FLAGS_benchmark")
        t_dev = _time.perf_counter() if rec is not None else 0.0
        with RecordEvent("Executor::run"), _tracing.span("device"):
            try:
                from ..distributed.faults import oom_point

                oom_point("run")
                fetches, new_state, new_key = compiled.fn(
                    feed_arrays, donated, kept, scope._rng_key
                )
            except Exception as e:
                from ..telemetry import memory as _memory

                if not isinstance(e, _memory.HBMOOMError) \
                        and _memory.is_oom(e):
                    # allocator OOM mid-step (jit compiles lazily, so a
                    # first-call compile OOM lands here too): the OOM
                    # doctor dumps the memory flight-record and raises
                    # with the culprit buffer + what-ifs attached
                    _memory.raise_oom(program, feed_arrays, phase="run",
                                      error=e)
                raise
            if rec is not None and bench:
                # honest device time needs a fence; gated on the same
                # FLAGS_benchmark that already syncs below, so telemetry
                # never adds a fence the run didn't opt into
                import jax

                jax.block_until_ready(fetches)
                rec.fenced = True
        if rec is not None:
            dt = (_time.perf_counter() - t_dev) * 1e3
            if rec.cache_hit:
                rec.device_ms += dt
            else:
                # jax.jit compiles lazily: on a cache-miss step XLA's
                # compile happens INSIDE this first call, so the window
                # belongs to compile_ms — device_ms would otherwise
                # spike once per signature and poison step-time stats
                rec.compile_ms += dt
        if check_numerics:
            # bad-step guard (FLAGS_check_numerics): refuse to COMMIT a
            # step whose gradients went non-finite — scope (params,
            # moments, RNG key) stays exactly pre-step, so the caller
            # can skip the batch or roll back. Raised before check_nan:
            # skip semantics win over fail-fast when both are on.
            bad = self._scan_bad_step(new_state)
            if bad is not None:
                from .checkpoint import BadStepError

                # flight recorder: the spans that led to the bad step
                # are evidence — dump them BEFORE the raise unwinds
                # (no-op unless PADDLE_TRACING + PADDLE_TRACE_DIR)
                _tracing.annotate(bad_step=bad)
                _tracing.flight_dump("bad_step")
                # NaN-provenance doctor: the scope is still exactly
                # pre-step, so the failed step can be replayed eagerly
                # and bisected to its FIRST non-finite producer; the
                # numrec dump + report ride the BadStepError
                report, dump = _numerics.maybe_run_doctor(
                    program, feed_arrays, scope, reason=bad)
                detail = ""
                if report and report.get("provenance") == "op":
                    uf = report.get("user_frame")
                    detail = (
                        f"; first non-finite producer: "
                        f"op#{report['op_index']} "
                        f"[{report['op_type']}] -> "
                        f"{report['output_var']!r}"
                        + (f" at {uf[0]}:{uf[1]}" if uf else ""))
                if dump:
                    detail += f"; numerics flight-record: {dump}"
                raise BadStepError(
                    f"FLAGS_check_numerics: {bad}; step NOT committed "
                    f"(parameters, optimizer state and RNG unchanged)"
                    f"{detail}", report=report, dump_path=dump)
        if check_nan:
            # reference FLAGS_check_nan_inf scans every op output
            # (operator.cc:1020); with whole-block XLA compilation the
            # intermediates never materialize, so the per-step contract
            # here is: every fetch and every updated state var is finite.
            # Checked BEFORE committing to the scope, so a handler can
            # checkpoint/retry from the last good parameters
            self._check_nan_inf(fetch_names, fetches, new_state)
        scope._rng_key = new_key
        for n, v in new_state.items():
            scope.set_var(n, v)
        # numerics observability (ISSUE 12): sampled stat-var reads, AMP
        # scale transitions, SDC fingerprint publishing. Unarmed cost:
        # two attribute reads (the bit-identity contract)
        _numerics.on_step_commit(program, new_state)
        if bench:
            import jax

            jax.block_until_ready(fetches)
        if return_numpy:
            with RecordEvent("Executor::fetch"), _tracing.span("fetch"):
                t_f = _time.perf_counter() if rec is not None else 0.0
                out = [np.asarray(f) for f in fetches]
                if rec is not None:
                    rec.fetch_ms += (_time.perf_counter() - t_f) * 1e3
                return out
        return list(fetches)

    @staticmethod
    def _scan_bad_step(new_state):
        """Guard-var check for FLAGS_check_numerics. Programs built with
        the flag on carry one or more `check_numerics_bad_*` persistable
        vars (Optimizer._append_check_numerics_guard: an in-graph
        any-grad-non-finite reduction — grads are fused intermediates
        the host could never scan). Programs without a guard var (built
        flag-off, or no optimizer) fall back to scanning the updated
        state itself. Returns a description of the violation or None."""
        import jax.numpy as jnp

        guard_vals = {n: v for n, v in new_state.items()
                      if n.startswith("check_numerics_bad")}
        if guard_vals:
            for n, v in guard_vals.items():
                if bool(jnp.any(jnp.asarray(v) != 0)):
                    if n.startswith("check_numerics_bad_amp"):
                        return (f"AMP loss-scale backoff exhausted: "
                                f"overflow below the scale floor "
                                f"(guard {n!r})")
                    return f"non-finite gradient detected (guard {n!r})"
            return None
        for n, v in new_state.items():
            try:
                ok = bool(jnp.all(jnp.isfinite(v)))
            except TypeError:  # non-float state (ints, keys)
                continue
            if not ok:
                return f"variable {n!r} would become non-finite"
        return None

    @staticmethod
    def _check_nan_inf(fetch_names, fetches, new_state):
        import jax.numpy as jnp

        def bad(v):
            try:
                return not bool(jnp.all(jnp.isfinite(v)))
            except TypeError:  # non-float (ints, keys)
                return False

        for name, v in zip(fetch_names, fetches):
            if bad(v):
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: fetch {name!r} contains NaN/Inf"
                )
        for name, v in new_state.items():
            if bad(v):
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: variable {name!r} contains NaN/Inf "
                    f"after this step"
                )

    # ------------------------------------------------------------------
    def _ensure_compiled(self, program, block, feed_arrays, fetch_names,
                         scope, no_donate):
        """Fetch-or-build the compiled step for this cache key. Shared by
        run() and memory_analysis() so both agree on compile semantics
        (and memory_analysis can compile WITHOUT executing). no_donate:
        diagnostic/guard modes (check_nan_inf, check_numerics) must keep
        the pre-step buffers alive."""
        key = self._cache_key(program, feed_arrays, fetch_names, no_donate)
        compiled = self._cache.get(key)
        if compiled is None:
            from .flags import flag

            if flag("FLAGS_program_verify"):
                # static verification BEFORE XLA sees the block: a
                # malformed graph raises a ProgramVerifyError pointing
                # at the op's build-time call stack instead of a trace
                # error hundreds of frames deep. Flag-off cost: this one
                # dict lookup, only on a compile-cache miss.
                from .analysis import assert_valid

                assert_valid(
                    program,
                    live_out=set(feed_arrays) | set(fetch_names),
                    where="Executor compile (FLAGS_program_verify)")
                # scope-aware lint (same flag, same first-touch site):
                # every persistable the program reads before writing
                # must already be in the scope, initialized, with
                # matching shape/dtype — the finding names the var and
                # the owning layer instead of failing inside jit.
                # Orphan-scope warnings are skipped here: scopes are
                # routinely shared across programs (startup then main).
                from .analysis import assert_scope_valid

                assert_scope_valid(
                    program, scope, feed_names=set(feed_arrays),
                    check_orphans=False,
                    where="Executor compile (FLAGS_program_verify)")
            # a RETRACE is a recompile of a program the cache already
            # holds under another signature (shape change, new fetch
            # list, flag toggle) — the shape-instability tax telemetry
            # counts separately from first compiles
            retrace = any(k[0] == program._serial for k in self._cache)
            # memory observability (ISSUE 11): FLAGS_mem_profile runs
            # the static live-range pass and publishes /memz + gauges;
            # PADDLE_HBM_BUDGET_BYTES gates the static estimate BEFORE
            # paying (or failing) the XLA compile. Flag-off + env-unset
            # cost: one flag read + one env read on a cache miss.
            from ..telemetry import memory as _memory

            _memory.on_compile(program, feed_arrays, fetch_names)
            import time as _time

            t0 = _time.perf_counter()
            try:
                with RecordEvent("Executor::compile"), \
                        _tracing.span("compile",
                                      attrs={"retrace": retrace}):
                    from ..distributed.faults import oom_point

                    oom_point("compile")
                    compiled = self._compile(
                        program, block, sorted(feed_arrays), fetch_names,
                        scope, donate=not no_donate,
                    )
            except _memory.HBMOOMError:
                raise
            except Exception as e:
                if _memory.is_oom(e):
                    # OOM doctor: XLA refused at buffer assignment —
                    # dump the memory flight-record naming the largest
                    # live buffers + what-ifs, then raise enriched
                    _memory.raise_oom(program, feed_arrays,
                                      phase="compile", error=e)
                raise
            monitor.record_compile((_time.perf_counter() - t0) * 1e3,
                                   retrace)
            self._cache[key] = compiled
        else:
            monitor.record_cache_hit()
        return compiled

    @staticmethod
    def _ensure_rng(scope, program):
        """Initialize the scope's PRNG key once. TPU: the rbg generator
        lowers to the hardware RNG; threefry costs real step time for
        dropout masks (profiled ~7% on BERT-base). CPU keeps threefry
        for cross-run determinism."""
        if scope._rng_key is None:
            import jax

            if jax.default_backend() in ("tpu", "axon"):
                # typed key: fold_in/split/bernoulli all stay rbg
                scope._rng_key = jax.random.key(
                    program.random_seed or 0, impl="rbg"
                )
            else:
                scope._rng_key = jax.random.PRNGKey(program.random_seed or 0)

    @staticmethod
    def _cache_key(program, feed_arrays, fetch_names, no_donate):
        """THE compile-cache key — run() and memory_analysis() must agree
        on its exact shape, so both build it here."""
        feed_sig = tuple(
            (n, tuple(a.shape), str(a.dtype))
            for n, a in sorted(feed_arrays.items())
        )
        from .flags import flag

        # diagnostic flags belong in the key: toggling one to debug must
        # recompile, not silently hit the pre-toggle cache entry
        # (FLAGS_op_profile changes the traced computation's metadata, so
        # toggling it back off must return to the scope-free executable)
        # cache_signature() is None with FLAGS_kernel_autotune off (key
        # unchanged vs a build without the tuning layer) and the active
        # tuning-cache fingerprint with it on, so an edited cache — or a
        # search-harness override — retraces with the new kernel configs
        from .. import tuning

        return (program._serial, program._version, feed_sig, fetch_names,
                no_donate, flag("FLAGS_enable_unused_var_check"),
                flag("FLAGS_program_verify"), flag("FLAGS_op_profile"),
                flag("FLAGS_tensor_stats"), tuning.cache_signature())

    def _prepare_feed(self, block, feed):
        import jax

        out = {}
        for name, value in feed.items():
            if isinstance(value, jax.Array):
                # device-resident feed: zero host->device traffic per step.
                # The TPU answer to the reference's double-buffered reader
                # (operators/reader/buffered_reader.cc async GPU copy):
                # callers (DataLoader, bench) device_put batches ahead of
                # the step that consumes them.
                out[name] = value
                continue
            if block.has_var(name):
                var = block.var(name)
                arr = np.asarray(value)
                if arr.dtype != var.dtype and var.dtype is not None:
                    arr = arr.astype(var.dtype)
                out[name] = arr
            else:
                out[name] = np.asarray(value)
        return out

    def _compile(self, program, block, feed_names, fetch_names, scope,
                 donate=True):
        import jax

        ops = list(block.ops)
        # classify variables: reads before writes must come from feed or scope
        written: set = set(feed_names)
        state_in: List[str] = []
        for op in ops:
            spec = registry.get(op.type)
            if spec is None:
                raise KeyError(f"op {op.type!r} has no registered emitter")
            for n in op.input_names():
                if n not in written and n not in state_in:
                    state_in.append(n)
            for n in op.output_names():
                written.add(n)

        from .flags import flag

        if flag("FLAGS_enable_unused_var_check"):
            # reference unused_var_check.cc (FLAGS_enable_unused_var_check,
            # operator.cc:987): surface feeds no op consumes — the
            # classic silently-ignored-input bug. Sub-block programs read
            # outer vars through their own ops, so only block-0 feeds
            # are checkable here; fetch-only feeds are legitimate.
            consumed = {
                n for b in program.blocks for op in b.ops
                for n in op.input_names()
            }
            unused = [n for n in feed_names
                      if n not in consumed and n not in fetch_names]
            if unused:
                import warnings

                # _compile <- _ensure_compiled <- run <- user call site
                warnings.warn(
                    f"Executor: feed variable(s) {unused} are consumed "
                    f"by no op in the program (FLAGS_enable_unused_var_"
                    f"check) — a misspelled feed name or dead input?",
                    RuntimeWarning, stacklevel=4)
        # fetches that are pure feeds/state also work
        for n in fetch_names:
            if n not in written and n not in state_in and n not in feed_names:
                state_in.append(n)

        persistable = {
            v.name
            for v in program.list_vars()
            if v.persistable
        }
        state_out = [
            n
            for n in dict.fromkeys(
                n for op in ops for n in op.output_names()
            )
            if n in persistable or scope.find_var(n) is not None
        ]

        donate_names = [n for n in state_in if n in set(state_out)]
        keep_names = [n for n in state_in if n not in set(state_out)]
        mesh = program._mesh
        # captured at compile time (the flag is in the cache key): per-op
        # named scopes for device-time attribution (telemetry/cost.py)
        op_profile = flag("FLAGS_op_profile")

        import contextlib

        def fwk_scope(name):
            # framework epilogue compute (rng advance, fetch sync) gets
            # its own named scope under FLAGS_op_profile: real device
            # time that belongs to no Program op, but must still be
            # NAMED in the cost report instead of diluting coverage
            return (jax.named_scope(f"fwk:{name}") if op_profile
                    else contextlib.nullcontext())

        def fn(feed_vals, donated_vals, kept_vals, rng_key):
            ctx = registry.EmitContext(rng_key=rng_key, mesh=mesh,
                                       op_scopes=op_profile)
            env: Dict[str, Any] = {}
            env.update(kept_vals)
            env.update(donated_vals)
            env.update(feed_vals)
            registry.emit_ops(ctx, ops, env)
            fetches = [env[n] for n in fetch_names]
            new_state = {n: env[n] for n in state_out}
            # advance the scope key even if no op split it, so salted_rng
            # (per-op fold_in of the base key) differs across steps
            with fwk_scope("rng_advance"):
                next_key = jax.random.fold_in(ctx.rng_state, 0x5EED)
            return fetches, new_state, next_key

        manual_axes = getattr(program, "_manual_axes", None)
        if mesh is not None and manual_axes:
            # Manual multi-slice path (fleet hybrid_dcn): the whole step
            # runs inside shard_map over (dcn, dp) so per-shard gradients
            # stay VISIBLE — the program's c_dcn_grad_sync ops own the
            # two-level reduction (dense pmean over ICI, dense-or-DGC
            # over DCN) that GSPMD would otherwise fuse into one opaque
            # all-reduce. Parameters/optimizer state ride replicated;
            # identical synced grads keep them bitwise in lockstep.
            # Restriction (documented in fleet): data-parallel programs —
            # per-shard-divergent state like BN running stats is not
            # representable under the replicated out_specs.
            from jax.sharding import NamedSharding, PartitionSpec

            from ..compat import shard_map

            gblock = program.global_block()

            def pspec(name):
                v = gblock._find_var_recursive(name)
                spec = getattr(v, "_sharding", None) if v is not None else None
                if spec is None:
                    return PartitionSpec()
                return spec if isinstance(spec, PartitionSpec) else PartitionSpec(*spec)

            repl_p = PartitionSpec()
            axis_sizes = [mesh.shape[a] for a in manual_axes]

            # LocalSGD-style per-slice divergent state: stored/sharded as
            # [n_dcn, *shape] over "dcn", but ops consume the plain
            # [*shape] local view — squeeze on entry, restore on exit
            divergent = set(getattr(program, "_dcn_divergent_names", ()))

            def local_fn(feed_vals, donated_vals, kept_vals, rng_key):
                import jax.lax as lax
                import jax.numpy as jnp

                # decorrelate per-shard randomness (dropout draws differ
                # per data shard, like per-worker seeds in the reference);
                # the RETURNED key advances from the unsalted key so the
                # replicated out_spec holds
                with fwk_scope("rng_shard_salt"):
                    shard = lax.axis_index(manual_axes[0])
                    for ax, size in zip(manual_axes[1:], axis_sizes[1:]):
                        shard = shard * size + lax.axis_index(ax)
                    salted = jax.random.fold_in(rng_key, shard)
                ctx = registry.EmitContext(
                    rng_key=salted, mesh=None, manual_axes=manual_axes,
                    op_scopes=op_profile,
                )
                env: Dict[str, Any] = {}
                env.update(kept_vals)
                env.update(donated_vals)
                env.update(feed_vals)
                for n in divergent:
                    if n in env:
                        env[n] = jnp.squeeze(env[n], axis=0)
                registry.emit_ops(ctx, ops, env)
                for n in divergent:
                    if n in env:
                        env[n] = env[n][None]

                state_set = (
                    set(donate_names) | set(keep_names) | set(state_out)
                )

                def _sync(n, x):
                    # fetch contract: state vars are replicated already;
                    # scalar floats are per-shard batch metrics (mean of
                    # means == global mean); everything else is
                    # batch-sharded on dim 0 — gather it back to the
                    # global batch instead of silently averaging shards
                    if n in state_set:
                        return x
                    xa = jnp.asarray(x)
                    if xa.ndim == 0 or xa.size == 1:
                        if jnp.issubdtype(xa.dtype, jnp.floating):
                            return lax.pmean(x, manual_axes)
                        # ADVICE r3: an integer scalar is ambiguous here
                        # (per-shard count -> psum, replicated value ->
                        # identity); silently returning one shard's value
                        # was wrong either way — make the caller choose
                        raise TypeError(
                            f"manual-mesh fetch {n!r} is a non-float "
                            f"scalar: per-shard integer metrics have no "
                            f"canonical global reduction — cast it to "
                            f"float32 in-program (mean semantics) or sum "
                            f"counts in-program before fetching"
                        )
                    return lax.all_gather(x, manual_axes, axis=0, tiled=True)

                with fwk_scope("fetch_sync"):
                    fetches = [_sync(n, env[n]) for n in fetch_names]
                new_state = {n: env[n] for n in state_out}
                with fwk_scope("rng_advance"):
                    next_key = jax.random.fold_in(rng_key, 0x5EED)
                return fetches, new_state, next_key

            # state vars default to replicated; vars annotated with a
            # sharding (the DGC per-slice error-feedback buffers, sharded
            # over "dcn") keep their per-shard identity through the specs
            in_specs = (
                {n: pspec(n) for n in feed_names},
                {n: pspec(n) for n in donate_names},
                {n: pspec(n) for n in keep_names},
                repl_p,
            )
            out_specs = (
                [repl_p for _ in fetch_names],
                {n: pspec(n) for n in state_out},
                repl_p,
            )
            wrapped = shard_map(
                local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check=False,
            )
            jit_fn = jax.jit(wrapped, donate_argnums=(1,) if donate else ())
            cb = _CompiledBlock(
                jit_fn, list(feed_names), donate_names, keep_names,
                state_out, fetch_names,
            )
            repl = NamedSharding(mesh, repl_p)
            cb.state_shardings = {
                n: NamedSharding(mesh, pspec(n))
                for n in donate_names + keep_names
            }
            cb.feed_shardings = {
                n: NamedSharding(mesh, pspec(n)) for n in feed_names
            }
            cb.repl_sharding = repl
            return cb

        if mesh is not None:
            # GSPMD path: every var maps to a NamedSharding (default
            # replicated); XLA SPMD inserts the collectives. This replaces
            # the reference's ParallelExecutor SSA-graph cloning + NCCL op
            # handles (parallel_executor.cc:470, details/all_reduce_op_handle.cc).
            from jax.sharding import NamedSharding, PartitionSpec

            gblock = program.global_block()

            def sh(name):
                v = gblock._find_var_recursive(name)
                spec = getattr(v, "_sharding", None) if v is not None else None
                return NamedSharding(mesh, spec if spec is not None else PartitionSpec())

            repl = NamedSharding(mesh, PartitionSpec())
            in_shardings = (
                {n: sh(n) for n in feed_names},
                {n: sh(n) for n in donate_names},
                {n: sh(n) for n in keep_names},
                repl,
            )
            out_shardings = (
                [sh(n) for n in fetch_names],
                {n: sh(n) for n in state_out},
                repl,
            )
            jit_fn = jax.jit(
                fn,
                donate_argnums=(1,) if donate else (),
                in_shardings=in_shardings,
                out_shardings=out_shardings,
            )
            cb = _CompiledBlock(
                jit_fn, list(feed_names), donate_names, keep_names, state_out, fetch_names
            )
            cb.state_shardings = {n: sh(n) for n in donate_names + keep_names}
            cb.feed_shardings = {n: sh(n) for n in feed_names}
            cb.repl_sharding = repl
            return cb
        jit_fn = jax.jit(fn, donate_argnums=(1,) if donate else ())
        return _CompiledBlock(
            jit_fn, list(feed_names), donate_names, keep_names, state_out, fetch_names
        )


    # ------------------------------------------------------------------
    def aot_step(self, program=None, feed=None, fetch_list=None,
                 scope=None):
        """AOT lower+compile the step for this (program, feed signature,
        fetch list) WITHOUT executing it, and return the jax Compiled
        object — the introspection handle behind memory_analysis()
        (.memory_analysis()), per-op cost attribution (.as_text(): the
        optimized HLO whose op_name metadata carries FLAGS_op_profile's
        op scopes — telemetry/cost.py joins xplane events through it)
        and measured flop counts (.cost_analysis()). Shares
        _ensure_compiled with run(), so the traced computation is the
        one the hot path executes; the AOT compile itself is a second
        XLA compile unless the persistent compilation cache is armed —
        diagnostics pricing, not per-step pricing."""
        import jax

        if program is None:
            program = framework.default_main_program()
        if hasattr(program, "_program"):
            program = program._program
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()
        fetch_names = tuple(
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        )
        block = program.global_block()
        feed_arrays = self._prepare_feed(block, feed)
        from .flags import flag

        # compile WITHOUT executing: callers can ask "does this step fit
        # HBM?" BEFORE paying (or failing with an allocator OOM) the
        # first run — the auto-remat escalation path in bench.py. The
        # block is cached, so a subsequent run() reuses it.
        compiled = self._ensure_compiled(
            program, block, feed_arrays, fetch_names, scope,
            flag("FLAGS_check_nan_inf") or flag("FLAGS_check_numerics"),
        )
        self._ensure_rng(scope, program)
        states = {
            n: scope.find_var(n)
            for n in (compiled.donate_names + compiled.keep_names)
        }
        rng = scope._rng_key
        if any(v is None for v in states.values()):
            raise RuntimeError(
                "aot_step: run the startup program first in the "
                "SAME scope — the analysis abstracts the scope's state"
            )

        def _abstract(x):
            a = np.asarray(x) if not hasattr(x, "dtype") else x
            return jax.ShapeDtypeStruct(np.shape(a), a.dtype)

        donated = {n: _abstract(states[n]) for n in compiled.donate_names}
        kept = {n: _abstract(states[n]) for n in compiled.keep_names}
        feeds_abs = {n: _abstract(a) for n, a in feed_arrays.items()}
        rng_abs = jax.ShapeDtypeStruct(np.shape(rng), rng.dtype)
        return compiled.fn.lower(feeds_abs, donated, kept, rng_abs).compile()

    def memory_analysis(self, program=None, feed=None, fetch_list=None,
                        scope=None):
        """XLA's buffer-assignment memory numbers for the compiled step
        (the measured answer to "does this batch fit?" — reference-era
        practice was trial-and-error against the allocator). Returns a
        dict with argument/output/temp/alias bytes and the derived
        `peak_bytes` (arguments + outputs + temps - aliased, XLA's HBM
        high-water estimate for one execution).

        The STARTUP program must have been run first in the given scope
        (the analysis abstracts the scope's live state); the step program
        itself is compiled on demand WITHOUT executing, so callers can
        probe "does this config fit HBM?" before the first step — the
        bench's auto-remat escalation relies on this. Cost note: the AOT
        lower().compile() does not share jax.jit's per-call executable
        cache — unless the persistent XLA compilation cache is
        configured, this pays one extra compile of the step; call it for
        config probing / diagnostics, not per step.
        """
        ma = self.aot_step(program, feed, fetch_list, scope).memory_analysis()
        out = {}
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
            out[k] = int(getattr(ma, k, 0) or 0)
        out["peak_bytes"] = (
            out["argument_size_in_bytes"] + out["output_size_in_bytes"]
            + out["temp_size_in_bytes"] - out["alias_size_in_bytes"]
        )
        return out

    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           checkpoint_dir=None, checkpoint_freq=0,
                           checkpoint_keep=3, resume=False):
        """Train by streaming batches from a Dataset (reference
        executor.py:1546 → C++ MultiTrainer/HogwildWorker hot loop,
        hogwild_worker.cc:191). The TPU executor has no per-thread scopes:
        the dataset iterator feeds the one compiled step, which is already
        the whole fwd+bwd+update program.

        checkpoint_dir arms the preemption-safe layer
        (fluid/checkpoint.py): every `checkpoint_freq` consumed batches
        the full training state (persistables, RNG, reader position) is
        committed atomically; resume=True restores the newest VALID
        checkpoint and skips the already-consumed batches, continuing
        with a bit-identical loss trace; a SIGTERM (or
        checkpoint.request_preemption()) gets a final checkpoint and
        raises checkpoint.Preempted. Under FLAGS_check_numerics a bad
        step is skipped, and after FLAGS_check_numerics_max_bad_steps
        consecutive bad steps the run rolls back to the last checkpoint
        (re-reading the dataset from its recorded position)."""
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        if thread:
            dataset.set_thread(thread)
        fetch_list = list(fetch_list or [])
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        from . import checkpoint as ckpt_mod
        from .flags import flag

        mgr = None
        consumed = 0
        if checkpoint_dir:
            if program is None:
                program = framework.default_main_program()
            if hasattr(program, "_program"):
                program = program._program
            mgr = ckpt_mod.CheckpointManager(
                checkpoint_dir, keep_last_n=checkpoint_keep,
                program=program, scope=scope or global_scope())
            ckpt_mod.install_preemption_handler()
            if resume:
                st = mgr.restore()
                if st is not None:
                    consumed = int(st["extra"].get("consumed_batches", 0))
        max_bad = max(1, int(flag("FLAGS_check_numerics_max_bad_steps")))
        bad_streak, last_rollback_sig = 0, None
        last = None
        while True:
            rolled_back = False
            step = 0
            # timed_iter: time blocked on the input iterator lands in
            # the next step record's data_wait_ms (no-op when off)
            for feed in monitor.timed_iter(dataset._as_loader(drop_last=True)):
                if step < consumed:  # replaying up to the restored position
                    step += 1
                    continue
                if mgr is not None:
                    # surface a latched async-writer failure at the
                    # step boundary (fluid/checkpoint.py error latch)
                    mgr.raise_if_async_failed()
                if mgr is not None and ckpt_mod.preemption_requested():
                    # final checkpoint is synchronous: supersede any
                    # queued async snapshot, wait out an in-flight
                    # write, commit before exiting
                    mgr.save(step, extra_state={"consumed_batches": step},
                             async_=False)
                    raise ckpt_mod.Preempted(
                        f"preemption requested: checkpointed at batch "
                        f"{step} in {checkpoint_dir!r}")
                try:
                    last = self.run(program, feed=feed,
                                    fetch_list=fetch_names, scope=scope)
                except ckpt_mod.BadStepError:
                    bad_streak += 1
                    if bad_streak >= max_bad:
                        # same-position repeat streak: the replay
                        # re-diverged deterministically — propagate
                        # instead of rolling back forever
                        sig = step - bad_streak + 1
                        if (mgr is None or mgr.latest_step() is None
                                or sig == last_rollback_sig):
                            raise
                        last_rollback_sig = sig
                        st = mgr.restore()
                        consumed = int(
                            st["extra"].get("consumed_batches", 0))
                        bad_streak = 0
                        rolled_back = True
                        break
                    step += 1  # skip the poisoned batch, keep training
                    continue
                bad_streak = 0
                if debug and fetch_names and step % print_period == 0:
                    info = fetch_info or fetch_names
                    vals = ", ".join(
                        f"{n}={np.asarray(v).reshape(-1)[0]:.6f}"
                        for n, v in zip(info, last)
                    )
                    print(f"step {step}: {vals}")
                step += 1
                if (mgr is not None and checkpoint_freq
                        and step % checkpoint_freq == 0):
                    mgr.save(step, extra_state={"consumed_batches": step})
            if not rolled_back:
                if mgr is not None:
                    # return with the checkpoints ON DISK (drain any
                    # queued/in-flight async write, surface failures)
                    mgr.drain()
                return last

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Same loop over a test-mode program (reference executor.py)."""
        return self.train_from_dataset(
            program, dataset, scope, thread, debug, fetch_list, fetch_info,
            print_period,
        )


# parity alias: reference as_lodtensor etc. are unnecessary (numpy in/out)
