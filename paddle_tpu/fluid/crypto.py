"""Model encryption (reference paddle/fluid/framework/io/crypto/:
cipher.h:24 CipherFactory + aes_cipher.h:48 AESCipher, used to encrypt
saved inference models).

AES-256-GCM via the `cryptography` package: authenticated encryption
(the reference's AES-CBC+tag scheme modernized), random 96-bit nonce
prepended to the ciphertext. Keys are 32 raw bytes or any string
(hashed to 32 bytes with SHA-256, matching the reference's convert-key
helper behavior)."""
from __future__ import annotations

import hashlib
import os


def _key_bytes(key) -> bytes:
    if isinstance(key, str):
        key = key.encode()
    if len(key) != 32:
        key = hashlib.sha256(key).digest()
    return key


def encrypt_bytes(data: bytes, key) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    nonce = os.urandom(12)
    return nonce + AESGCM(_key_bytes(key)).encrypt(nonce, data, None)


def decrypt_bytes(data: bytes, key) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    return AESGCM(_key_bytes(key)).decrypt(data[:12], data[12:], None)


def encrypt_file(path: str, key, out_path=None) -> str:
    out_path = out_path or path
    with open(path, "rb") as f:
        data = f.read()
    with open(out_path, "wb") as f:
        f.write(encrypt_bytes(data, key))
    return out_path


def decrypt_file(path: str, key) -> bytes:
    with open(path, "rb") as f:
        return decrypt_bytes(f.read(), key)
