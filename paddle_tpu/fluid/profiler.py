"""Profiler: host event spans + device (XLA) trace -> chrome timeline.

Parity surface: reference platform/profiler.h:126 (RecordEvent),
EnableProfiler/DisableProfiler (:208,211), device_tracer.cc:61 (CUPTI
capture), python profiler.py:131,198,255 (start_profiler, stop_profiler,
profiler context manager) and tools/timeline.py (chrome trace export).

TPU-native design: host spans are recorded by a Python RecordEvent (the
executor wraps each run() in one); device-side timing comes from the JAX
/ XLA profiler (xplane), the TPU analog of CUPTI. stop_profiler writes
ONE chrome-trace JSON merging both (host pid 0, device pid 1 — open in
chrome://tracing or Perfetto), prints the reference-style summary table,
and leaves the raw xplane file beside it for xprof/tensorboard.
"""
from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_lock = threading.Lock()
_enabled = False
_events: List[tuple] = []  # (name, tid, start_ns, end_ns)
_trace_dir: Optional[str] = None
_device_tracing = False


def is_profiler_enabled() -> bool:
    return _enabled


class RecordEvent:
    """RAII host span (reference platform/profiler.h:126). Usable as a
    context manager; zero cost when the profiler is off."""

    def __init__(self, name: str):
        self.name = name
        self._start = 0

    def __enter__(self):
        if _enabled:
            self._start = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if _enabled and self._start:
            with _lock:
                _events.append(
                    (self.name, threading.get_ident(), self._start,
                     time.perf_counter_ns())
                )
        return False


def reset_profiler():
    """reference profiler.py reset_profiler."""
    with _lock:
        _events.clear()


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """state: CPU (host spans only) | GPU/All (also start the XLA device
    trace — 'GPU' kept for API parity, it means 'device')."""
    global _enabled, _trace_dir, _device_tracing
    if _enabled:
        return
    reset_profiler()
    _enabled = True
    _trace_dir = None
    if state in ("GPU", "All"):
        import jax

        _trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
        try:
            jax.profiler.start_trace(_trace_dir)
            _device_tracing = True
        except Exception:  # noqa: BLE001 — device tracing is best-effort
            _device_tracing = False


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: str = "/tmp/profile"):
    """Stop, print the summary table, write `<profile_path>.json` (chrome
    trace) and leave the xplane dir (device) beside it."""
    global _enabled, _device_tracing, _trace_dir
    if not _enabled:
        return
    _enabled = False
    if _device_tracing:
        import jax

        jax.profiler.stop_trace()
        _device_tracing = False

    events = list(_events)
    _print_summary(events, sorted_key)
    # one time base for both pids: host spans use perf_counter_ns and the
    # xplane uses CLOCK_REALTIME-ish ns, so anchor each side to its own
    # first timestamp — the two tracks then align at t=0
    chrome = _host_chrome_events(events)
    chrome += _device_chrome_events(_trace_dir)
    out = profile_path if profile_path.endswith(".json") else profile_path + ".json"
    d = os.path.dirname(out)
    if d:  # dirless paths write to the cwd — nothing to create
        os.makedirs(d, exist_ok=True)
    with open(out, "w") as f:
        json.dump({"traceEvents": chrome, "displayTimeUnit": "ms"}, f)
    if _trace_dir:
        print(f"[profiler] chrome trace: {out}; raw xplane: {_trace_dir}")
    else:
        print(f"[profiler] chrome trace: {out}")
    _trace_dir = None


def export_chrome_trace(path: str) -> str:
    """SNAPSHOT the host spans recorded so far into a chrome-trace JSON
    WITHOUT stopping the profiler (events keep accumulating; device
    xplane events only appear in stop_profiler's trace — the device
    trace cannot be read mid-flight). The launcher's per-rank timeline
    collection (PADDLE_TRACE_DIR) uses exactly this. Returns the path
    written."""
    with _lock:
        events = list(_events)
    out = path if path.endswith(".json") else path + ".json"
    d = os.path.dirname(out)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": _host_chrome_events(events),
                   "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out)  # the launcher may merge while we run
    return out


_collection_started = False


def maybe_start_trace_collection() -> bool:
    """Launcher contract (launch.py --trace_dir): when PADDLE_TRACE_DIR
    is set, record host spans for the life of the process and dump
    `<dir>/trace.<rank>.json` at exit; the launcher merges the per-rank
    files into one timeline (telemetry.timeline). Called by
    parallel.env.init_parallel_env — launched trainers opt in without
    code changes. No-op (False) when the env var is unset."""
    global _collection_started, _enabled
    directory = os.environ.get("PADDLE_TRACE_DIR")
    if not directory or _collection_started:
        return _collection_started
    _collection_started = True
    _enabled = True  # host spans only; device tracing stays user-driven
    rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    path = os.path.join(directory, f"trace.{rank}.json")

    import atexit

    atexit.register(lambda: export_chrome_trace(path))
    return True


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = "total",
             profile_path: str = "/tmp/profile", tracer_option: str = "Default"):
    """reference profiler.py:255 context manager."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# summary + chrome trace assembly
# ---------------------------------------------------------------------------


def _print_summary(events, sorted_key):
    agg: Dict[str, List[float]] = {}
    for name, _tid, s, e in events:
        agg.setdefault(name, []).append((e - s) / 1e6)
    rows = []
    for name, durs in agg.items():
        rows.append((name, len(durs), sum(durs), sum(durs) / len(durs),
                     min(durs), max(durs)))
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2
    )
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    if not rows:
        return
    print(f"{'Event':<44}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
          f"{'Min(ms)':>10}{'Max(ms)':>10}")
    for r in rows:
        print(f"{r[0][:43]:<44}{r[1]:>8}{r[2]:>12.3f}{r[3]:>10.3f}"
              f"{r[4]:>10.3f}{r[5]:>10.3f}")


def _host_chrome_events(events):
    if not events:
        return []
    t0 = min(s for _, _, s, _ in events)
    out = [{"name": "process_name", "ph": "M", "pid": 0,
            "args": {"name": "host (python)"}}]
    for name, tid, s, e in events:
        out.append({
            "name": name, "ph": "X", "pid": 0, "tid": tid % 10_000,
            "ts": (s - t0) / 1e3, "dur": (e - s) / 1e3,
        })
    return out


def load_xplane(trace_dir) -> Optional[Any]:
    """Locate and parse the newest .xplane.pb under `trace_dir` into an
    XSpace proto. Best-effort, but never SILENT: when the device track
    is unavailable the reason is logged once, so a host-only trace (or
    an empty cost report) is explainable instead of mysterious. Returns
    None when the file or the schema is missing."""
    import sys
    import glob

    if not trace_dir:
        return None
    files = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True),
                   key=os.path.getmtime)
    if not files:
        print(f"[profiler] device track skipped: no .xplane.pb under "
              f"{trace_dir} (device tracing produced no output)",
              file=sys.stderr)
        return None
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception as e:  # noqa: BLE001 — schema unavailable: skip merge
        print(f"[profiler] device track skipped: xplane schema "
              f"unavailable ({type(e).__name__}: {e}); raw xplane kept "
              f"at {trace_dir} for xprof/tensorboard", file=sys.stderr)
        return None
    xs = xplane_pb2.XSpace()
    try:
        with open(files[-1], "rb") as f:
            xs.ParseFromString(f.read())
    except Exception as e:  # noqa: BLE001 — torn/foreign xplane file
        print(f"[profiler] device track skipped: failed to parse "
              f"{files[-1]} ({type(e).__name__}: {e})", file=sys.stderr)
        return None
    return xs


def xplane_op_events(source) -> Dict[str, Dict[str, Any]]:
    """Aggregate XLA op executions out of an xplane trace: HLO
    instruction name -> {dur_ps, count, flops, bytes_accessed,
    hlo_module}. `source` is a trace dir or an already-parsed XSpace.

    An event counts as an op execution when it carries an `hlo_op` stat
    (the CPU thunk executor and the GPU/TPU device planes both stamp
    one) or lives on an "XLA Ops" device line (TPU op track). Everything
    else — thunk scheduling, host python, allocator spans — is runtime
    overhead, not op time, and is excluded from both the numerator and
    the denominator of telemetry.cost's attribution coverage. Where the
    backend reports per-op flop counts / bytes accessed (TPU op
    profile), they ride along; the CPU backend reports none.

    Control-flow op events NEST: a `while` instruction's span contains
    its body's op executions, which the trace records as their own
    events — counting both would double-charge every scanned layer. Op
    events fully contained in an earlier-starting op event of the same
    plane are dropped: the outer instruction (which carries the op scope
    of the Program op that emitted the loop) is charged its whole span."""
    xs = load_xplane(source) if isinstance(source, str) else source
    out: Dict[str, Dict[str, Any]] = {}
    if xs is None:
        return out
    for plane in xs.planes:
        stat_names = {k: v.name for k, v in plane.stat_metadata.items()}
        candidates = []  # (start_ps, end_ps, name, stats)
        for line in plane.lines:
            line_is_op_track = "xla op" in (line.name or "").lower()
            base_ps = int(line.timestamp_ns) * 1000
            for ev in line.events:
                stats = {}
                for st in ev.stats:
                    sn = stat_names.get(st.metadata_id)
                    if sn:
                        stats[sn] = (st.str_value or st.int64_value
                                     or st.uint64_value or st.double_value
                                     or st.ref_value)
                if "hlo_op" not in stats and not line_is_op_track:
                    continue
                meta = plane.event_metadata[ev.metadata_id]
                name = meta.name or str(ev.metadata_id)
                start = base_ps + int(ev.offset_ps)
                candidates.append(
                    (start, start + int(ev.duration_ps), name, stats))
        # drop op events nested inside another op event (strict interval
        # containment): sort by (start, -end) so an outer span precedes
        # its children; `actives` holds kept spans still open
        candidates.sort(key=lambda c: (c[0], -c[1]))
        actives: List[Tuple[int, int]] = []
        for start, end, name, stats in candidates:
            actives = [a for a in actives if a[1] > start]
            if any(a[0] <= start and end <= a[1] for a in actives):
                continue
            actives.append((start, end))
            row = out.setdefault(name, {
                "dur_ps": 0, "count": 0, "flops": 0.0,
                "bytes_accessed": 0, "hlo_module": None,
            })
            row["dur_ps"] += end - start
            row["count"] += 1
            for key in ("flops", "bytes_accessed"):
                v = stats.get(key)
                if isinstance(v, (int, float)) and v:
                    row[key] += v
            mod = stats.get("hlo_module")
            if isinstance(mod, str) and mod:
                row["hlo_module"] = mod
    return out


def _device_chrome_events(trace_dir):
    """Parse the xplane protobuf into chrome events (device pid 1+)."""
    xs = load_xplane(trace_dir)
    if xs is None:
        return []
    out = []
    raw = []
    pid = 1
    for plane in xs.planes:
        if "TPU" not in plane.name and "CPU" not in plane.name.upper():
            continue
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"device: {plane.name}"}})
        for li, line in enumerate(plane.lines):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": li, "args": {"name": line.name or f"line{li}"}})
            for ev in line.events:
                meta = plane.event_metadata[ev.metadata_id]
                start_ns = line.timestamp_ns + ev.offset_ps / 1e3
                raw.append((meta.name[:120], pid, li, start_ns,
                            ev.duration_ps / 1e6))
        pid += 1
    if not raw:
        return out
    t0 = min(r[3] for r in raw)
    for name, p_, tid, start_ns, dur in raw:
        out.append({"name": name, "ph": "X", "pid": p_, "tid": tid,
                    "ts": (start_ns - t0) / 1e3, "dur": dur})
    return out
