"""Profiler: host event spans + device (XLA) trace -> chrome timeline.

Parity surface: reference platform/profiler.h:126 (RecordEvent),
EnableProfiler/DisableProfiler (:208,211), device_tracer.cc:61 (CUPTI
capture), python profiler.py:131,198,255 (start_profiler, stop_profiler,
profiler context manager) and tools/timeline.py (chrome trace export).

TPU-native design: host spans are recorded by a Python RecordEvent (the
executor wraps each run() in one); device-side timing comes from the JAX
/ XLA profiler (xplane), the TPU analog of CUPTI. stop_profiler writes
ONE chrome-trace JSON merging both (host pid 0, device pid 1 — open in
chrome://tracing or Perfetto), prints the reference-style summary table,
and leaves the raw xplane file beside it for xprof/tensorboard.
"""
from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_enabled = False
_events: List[tuple] = []  # (name, tid, start_ns, end_ns)
_trace_dir: Optional[str] = None
_device_tracing = False


def is_profiler_enabled() -> bool:
    return _enabled


class RecordEvent:
    """RAII host span (reference platform/profiler.h:126). Usable as a
    context manager; zero cost when the profiler is off."""

    def __init__(self, name: str):
        self.name = name
        self._start = 0

    def __enter__(self):
        if _enabled:
            self._start = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if _enabled and self._start:
            with _lock:
                _events.append(
                    (self.name, threading.get_ident(), self._start,
                     time.perf_counter_ns())
                )
        return False


def reset_profiler():
    """reference profiler.py reset_profiler."""
    with _lock:
        _events.clear()


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """state: CPU (host spans only) | GPU/All (also start the XLA device
    trace — 'GPU' kept for API parity, it means 'device')."""
    global _enabled, _trace_dir, _device_tracing
    if _enabled:
        return
    reset_profiler()
    _enabled = True
    _trace_dir = None
    if state in ("GPU", "All"):
        import jax

        _trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
        try:
            jax.profiler.start_trace(_trace_dir)
            _device_tracing = True
        except Exception:  # noqa: BLE001 — device tracing is best-effort
            _device_tracing = False


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: str = "/tmp/profile"):
    """Stop, print the summary table, write `<profile_path>.json` (chrome
    trace) and leave the xplane dir (device) beside it."""
    global _enabled, _device_tracing, _trace_dir
    if not _enabled:
        return
    _enabled = False
    if _device_tracing:
        import jax

        jax.profiler.stop_trace()
        _device_tracing = False

    events = list(_events)
    _print_summary(events, sorted_key)
    # one time base for both pids: host spans use perf_counter_ns and the
    # xplane uses CLOCK_REALTIME-ish ns, so anchor each side to its own
    # first timestamp — the two tracks then align at t=0
    chrome = _host_chrome_events(events)
    chrome += _device_chrome_events(_trace_dir)
    out = profile_path if profile_path.endswith(".json") else profile_path + ".json"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump({"traceEvents": chrome, "displayTimeUnit": "ms"}, f)
    if _trace_dir:
        print(f"[profiler] chrome trace: {out}; raw xplane: {_trace_dir}")
    else:
        print(f"[profiler] chrome trace: {out}")
    _trace_dir = None


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = "total",
             profile_path: str = "/tmp/profile", tracer_option: str = "Default"):
    """reference profiler.py:255 context manager."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# summary + chrome trace assembly
# ---------------------------------------------------------------------------


def _print_summary(events, sorted_key):
    agg: Dict[str, List[float]] = {}
    for name, _tid, s, e in events:
        agg.setdefault(name, []).append((e - s) / 1e6)
    rows = []
    for name, durs in agg.items():
        rows.append((name, len(durs), sum(durs), sum(durs) / len(durs),
                     min(durs), max(durs)))
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2
    )
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    if not rows:
        return
    print(f"{'Event':<44}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
          f"{'Min(ms)':>10}{'Max(ms)':>10}")
    for r in rows:
        print(f"{r[0][:43]:<44}{r[1]:>8}{r[2]:>12.3f}{r[3]:>10.3f}"
              f"{r[4]:>10.3f}{r[5]:>10.3f}")


def _host_chrome_events(events):
    if not events:
        return []
    t0 = min(s for _, _, s, _ in events)
    out = [{"name": "process_name", "ph": "M", "pid": 0,
            "args": {"name": "host (python)"}}]
    for name, tid, s, e in events:
        out.append({
            "name": name, "ph": "X", "pid": 0, "tid": tid % 10_000,
            "ts": (s - t0) / 1e3, "dur": (e - s) / 1e3,
        })
    return out


def _device_chrome_events(trace_dir):
    """Parse the xplane protobuf into chrome events (device pid 1+).
    Best-effort: returns [] when the xplane schema is unavailable."""
    if not trace_dir:
        return []
    import glob

    files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not files:
        return []
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:  # noqa: BLE001 — schema unavailable: skip merge
        return []
    xs = xplane_pb2.XSpace()
    with open(files[0], "rb") as f:
        xs.ParseFromString(f.read())
    out = []
    raw = []
    pid = 1
    for plane in xs.planes:
        if "TPU" not in plane.name and "CPU" not in plane.name.upper():
            continue
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"device: {plane.name}"}})
        for li, line in enumerate(plane.lines):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": li, "args": {"name": line.name or f"line{li}"}})
            for ev in line.events:
                meta = plane.event_metadata[ev.metadata_id]
                start_ns = line.timestamp_ns + ev.offset_ps / 1e3
                raw.append((meta.name[:120], pid, li, start_ns,
                            ev.duration_ps / 1e6))
        pid += 1
    if not raw:
        return out
    t0 = min(r[3] for r in raw)
    for name, p_, tid, start_ns, dur in raw:
        out.append({"name": name, "ph": "X", "pid": p_, "tid": tid,
                    "ts": (start_ns - t0) / 1e3, "dur": dur})
    return out
