"""Optimizers: build backward + parameter-update ops into the program.

Parity surface: python/paddle/fluid/optimizer.py (Optimizer:55 and the 18
subclasses :913-5171). Updates are emitted as ops (operators/optimizers/ in
the reference), so the Executor compiles forward+backward+update into one
XLA computation per step — parameters never leave device memory.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import framework, unique_name
from .backward import append_backward
from .clip import GradientClipBase
from .framework import Parameter, Program, Variable, program_guard
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops


class Optimizer:
    def __init__(
        self,
        learning_rate=0.001,
        parameter_list=None,
        regularization=None,
        grad_clip: Optional[GradientClipBase] = None,
        name=None,
    ):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self.type = getattr(self, "type", "optimizer")
        self._learning_rate_var: Optional[Variable] = None
        # accumulators: name -> {param_name: var}
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self.helper: Optional[LayerHelper] = None

    # ------------------------------------------------------------------
    def _create_global_learning_rate(self):
        if self._learning_rate_var is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        lr_name = unique_name.generate("learning_rate")
        main_block = framework.default_main_program().global_block()
        self._learning_rate_var = main_block.create_var(
            name=lr_name, shape=(1,), dtype="float32", persistable=True
        )
        startup_block = framework.default_startup_program().global_block()
        sv = startup_block.create_var(
            name=lr_name, shape=(1,), dtype="float32", persistable=True
        )
        ConstantInitializer(float(self._learning_rate))(sv, startup_block)

    def _global_learning_rate(self):
        return self._learning_rate_var

    @property
    def current_step_lr(self):
        return self._learning_rate

    def set_lr(self, value):
        """Update the LR in-place (scope-level, no recompile needed)."""
        from .executor import global_scope

        self._learning_rate = value
        if self._learning_rate_var is not None:
            scope = global_scope()
            if scope.find_var(self._learning_rate_var.name) is not None:
                scope.set_var(
                    self._learning_rate_var.name,
                    np.full((1,), value, dtype=np.float32),
                )

    # ------------------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = tuple(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        main_block = framework.default_main_program().global_block()
        v = main_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        startup_block = framework.default_startup_program().global_block()
        sv = startup_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        ConstantInitializer(float(fill_value))(sv, startup_block)
        self._accumulators[name][param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # ------------------------------------------------------------------
    def backward(
        self,
        loss,
        startup_program=None,
        parameter_list=None,
        no_grad_set=None,
        callbacks=None,
    ):
        return append_backward(
            loss, parameter_list or self._parameter_list, no_grad_set, callbacks
        )

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        grad_clip = self._grad_clip
        if grad_clip is None and params_grads:
            # fluid.clip.set_gradient_clip() stores the clip on the program
            grad_clip = getattr(
                params_grads[0][0].block.program, "_grad_clip", None
            )
        if grad_clip is not None:
            params_grads = grad_clip(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        self._create_global_learning_rate()
        optimize_ops = []
        block = framework.default_main_program().global_block()
        self._create_accumulators(block, [p for p, _ in params_grads])
        for p, g in params_grads:
            if g is None:
                continue
            optimize_ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        return optimize_ops

    def apply_optimize(self, loss, startup_program, params_grads):
        with program_guard(loss.block.program, startup_program):
            return self.apply_gradients(params_grads)

    # -- dygraph (eager) path -------------------------------------------
    def _lr_value(self):
        """Current LR as a jax scalar array (dygraph path)."""
        import jax.numpy as jnp

        lr = self._learning_rate
        if isinstance(lr, Variable):
            raise TypeError(
                "dygraph mode needs a float learning rate (in-graph LR "
                "schedules are static-graph; use set_lr for manual decay)"
            )
        return jnp.full((1,), float(lr), jnp.float32)

    def minimize(
        self,
        loss,
        startup_program=None,
        parameter_list=None,
        no_grad_set=None,
    ):
        if framework.in_dygraph_mode():
            from .dygraph.optimizer_adapter import dygraph_step

            params = parameter_list or self._parameter_list
            if params is None:
                raise ValueError(
                    "dygraph minimize() needs parameter_list (pass "
                    "model.parameters() to the optimizer)"
                )
            dygraph_step(self, list(params))
            return [], []
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        # always anchor optimizer/LR ops to the loss's own program — the
        # default program may be a different one (reference optimizer.py
        # guards with loss.block.program in minimize)
        startup = (
            startup_program
            if startup_program is not None
            else framework.default_startup_program()
        )
        with program_guard(loss.block.program, startup):
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # subclass hooks -----------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p]},
        )


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    type = "lars_momentum"

    def __init__(
        self,
        learning_rate,
        momentum=0.9,
        lars_coeff=0.001,
        lars_weight_decay=0.0005,
        epsilon=0,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "epsilon": self._epsilon,
            },
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=(1,))
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=(1,))

    def _optimize_inputs_outputs(self, p, g):
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        inputs = {
            "Param": [p],
            "Grad": [g],
            "Moment1": [m1],
            "Moment2": [m2],
            "Beta1Pow": [b1p],
            "Beta2Pow": [b2p],
            "LearningRate": [self._learning_rate_var],
        }
        outputs = {
            "ParamOut": [p],
            "Moment1Out": [m1],
            "Moment2Out": [m2],
            "Beta1PowOut": [b1p],
            "Beta2PowOut": [b2p],
        }
        return inputs, outputs

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs, outputs = self._optimize_inputs_outputs(p, g)
        return block.append_op(
            type="adam",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )


class AdamWOptimizer(AdamOptimizer):
    type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, apply_decay_param_fun=None, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._weight_decay = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs, outputs = self._optimize_inputs_outputs(p, g)
        with_decay = True
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            with_decay = False
        return block.append_op(
            type="adamw",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "coeff": self._weight_decay,
                "with_decay": with_decay,
            },
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [m],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon},
        )


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(
        self,
        learning_rate,
        rho=0.95,
        epsilon=1e-6,
        momentum=0.0,
        centered=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum_acc", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs = {
            "Param": [p],
            "Grad": [g],
            "MeanSquare": [self._get_accumulator("mean_square", p)],
            "Moment": [self._get_accumulator("momentum_acc", p)],
            "LearningRate": [self._learning_rate_var],
        }
        outputs = {
            "ParamOut": [p],
            "MeanSquareOut": [self._get_accumulator("mean_square", p)],
            "MomentOut": [self._get_accumulator("momentum_acc", p)],
        }
        if self._centered:
            inputs["MeanGrad"] = [self._get_accumulator("mean_grad", p)]
            outputs["MeanGradOut"] = [self._get_accumulator("mean_grad", p)]
        return block.append_op(
            type="rmsprop",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class LambOptimizer(AdamOptimizer):
    type = "lamb"

    def __init__(
        self,
        learning_rate=0.001,
        lamb_weight_decay=0.01,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-6,
        exclude_from_weight_decay_fn=None,
        **kwargs,
    ):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs, outputs = self._optimize_inputs_outputs(p, g)
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        return block.append_op(
            type="lamb",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "weight_decay": wd,
            },
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [p],
                "Grad": [g],
                "SquaredAccumulator": [self._get_accumulator("squared", p)],
                "LinearAccumulator": [self._get_accumulator("linear", p)],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={
                "ParamOut": [p],
                "SquaredAccumOut": [self._get_accumulator("squared", p)],
                "LinearAccumOut": [self._get_accumulator("linear", p)],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class DpsgdOptimizer(Optimizer):
    type = "dpsgd"

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="dpsgd",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [self._learning_rate_var]},
            outputs={"ParamOut": [p]},
            attrs={
                "clip": self._clip,
                "batch_size": self._batch_size,
                "sigma": self._sigma,
            },
        )


# paddle-style short aliases (fluid.optimizer.SGD etc.)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adagrad = AdagradOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer
Ftrl = FtrlOptimizer
Dpsgd = DpsgdOptimizer
LarsMomentum = LarsMomentumOptimizer
