"""Optimizers: build backward + parameter-update ops into the program.

Parity surface: python/paddle/fluid/optimizer.py (Optimizer:55 and the 18
subclasses :913-5171). Updates are emitted as ops (operators/optimizers/ in
the reference), so the Executor compiles forward+backward+update into one
XLA computation per step — parameters never leave device memory.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import framework, unique_name
from .backward import append_backward
from .clip import GradientClipBase
from .framework import Parameter, Program, Variable, program_guard
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops


class Optimizer:
    def __init__(
        self,
        learning_rate=0.001,
        parameter_list=None,
        regularization=None,
        grad_clip: Optional[GradientClipBase] = None,
        name=None,
    ):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self.type = getattr(self, "type", "optimizer")
        self._learning_rate_var: Optional[Variable] = None
        # accumulators: name -> {param_name: var}
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self.helper: Optional[LayerHelper] = None

    # ------------------------------------------------------------------
    def _create_global_learning_rate(self):
        if self._learning_rate_var is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        lr_name = unique_name.generate("learning_rate")
        main_block = framework.default_main_program().global_block()
        self._learning_rate_var = main_block.create_var(
            name=lr_name, shape=(1,), dtype="float32", persistable=True
        )
        startup_block = framework.default_startup_program().global_block()
        sv = startup_block.create_var(
            name=lr_name, shape=(1,), dtype="float32", persistable=True
        )
        ConstantInitializer(float(self._learning_rate))(sv, startup_block)

    def _global_learning_rate(self):
        return self._learning_rate_var

    @property
    def current_step_lr(self):
        return self._learning_rate

    def set_lr(self, value):
        """Update the LR in-place (scope-level, no recompile needed)."""
        from .executor import global_scope

        self._learning_rate = value
        if self._learning_rate_var is not None:
            scope = global_scope()
            if scope.find_var(self._learning_rate_var.name) is not None:
                scope.set_var(
                    self._learning_rate_var.name,
                    np.full((1,), value, dtype=np.float32),
                )

    # ------------------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = tuple(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        main_block = framework.default_main_program().global_block()
        v = main_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        startup_block = framework.default_startup_program().global_block()
        sv = startup_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        ConstantInitializer(float(fill_value))(sv, startup_block)
        self._accumulators[name][param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # ------------------------------------------------------------------
    def backward(
        self,
        loss,
        startup_program=None,
        parameter_list=None,
        no_grad_set=None,
        callbacks=None,
    ):
        # graph-level fusion passes run BEFORE backward so grad synthesis
        # differentiates the fused ops (flag-gated no-op by default)
        from .fusion_pass import maybe_apply_conv_bn_fusion

        maybe_apply_conv_bn_fusion(loss.block.program)
        return append_backward(
            loss, parameter_list or self._parameter_list, no_grad_set, callbacks
        )

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        grad_clip = self._grad_clip
        if grad_clip is None and params_grads:
            # fluid.clip.set_gradient_clip() stores the clip on the program
            grad_clip = getattr(
                params_grads[0][0].block.program, "_grad_clip", None
            )
        if grad_clip is not None:
            params_grads = grad_clip(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        from .flags import flag as _flag

        if _flag("FLAGS_tensor_stats"):
            # numerics observability (ISSUE 12): one in-graph stats
            # reduction per applied gradient + parameter, AFTER clip +
            # regularization so the series shows what the update op
            # actually consumed. Flag-off: no ops, bit-identical build.
            from ..telemetry import numerics as _numerics

            _numerics.install_grad_stats(params_grads)
        if _flag("FLAGS_check_numerics"):
            self._append_check_numerics_guard(params_grads)
        self._create_global_learning_rate()
        optimize_ops = []
        block = framework.default_main_program().global_block()
        self._create_accumulators(block, [p for p, _ in params_grads])
        for p, g in params_grads:
            if g is None:
                continue
            optimize_ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        return optimize_ops

    def _append_check_numerics_guard(self, params_grads):
        """Bad-step guard (FLAGS_check_numerics), fp32 path: reduce
        every gradient to ONE persistable `check_numerics_bad_*` scalar
        (1.0 iff any grad holds NaN/Inf) inside the step program —
        gradients are fused XLA intermediates, so the host can only see
        them through an in-graph reduction like this (same technique as
        AMP's found_inf, which owns the fp16 path: under AMP the grads
        reaching this optimizer are already zeroed on overflow, so the
        guard stays silent there). Executor.run reads the guard from the
        step's state outputs and refuses to commit when it tripped."""
        from . import layers

        grads = [g for _, g in params_grads
                 if g is not None and str(g.dtype) in ("float32",
                                                       "float64")]
        if not grads:
            return
        bad = layers.fill_constant([1], "bool", 0.0)
        for g in grads:
            bad = layers.logical_or(
                bad,
                layers.logical_not(layers.reduce_all(layers.isfinite_v2(g))),
            )
        name = unique_name.generate("check_numerics_bad")
        main_block = framework.default_main_program().global_block()
        v = main_block.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True,
        )
        sblock = framework.default_startup_program().global_block()
        sv = sblock.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True
        )
        ConstantInitializer(0.0)(sv, sblock)
        layers.assign(layers.cast(bad, "float32"), v)

    def apply_optimize(self, loss, startup_program, params_grads):
        with program_guard(loss.block.program, startup_program):
            return self.apply_gradients(params_grads)

    # -- dygraph (eager) path -------------------------------------------
    def state_dict(self):
        """Dygraph accumulator state, {param_name: {accum_name: array}} —
        the save_dygraph .pdopt payload (reference optimizer.state_dict).
        Static-graph accumulators live in the scope and ride along with
        save_persistables / CheckpointManager instead."""
        return {
            pname: {k: np.asarray(v) for k, v in st.items()}
            for pname, st in getattr(self, "_eager_state", {}).items()
        }

    def set_state_dict(self, state_dict):
        """Restore dygraph accumulator state (load_dygraph's .pdopt dict).
        Keyed by parameter name: a fresh process re-building the same
        model reproduces the same names (unique_name restarts at 0),
        which is the resume contract."""
        self._eager_state = {
            pname: dict(st) for pname, st in (state_dict or {}).items()
        }

    # parity alias (reference exposes both spellings across versions)
    load_state_dict = set_state_dict

    def _lr_value(self):
        """Current LR as a jax scalar array (dygraph path)."""
        import jax.numpy as jnp

        lr = self._learning_rate
        if isinstance(lr, Variable):
            raise TypeError(
                "dygraph mode needs a float learning rate (in-graph LR "
                "schedules are static-graph; use set_lr for manual decay)"
            )
        return jnp.full((1,), float(lr), jnp.float32)

    def minimize(
        self,
        loss,
        startup_program=None,
        parameter_list=None,
        no_grad_set=None,
    ):
        if framework.in_dygraph_mode():
            from .dygraph.optimizer_adapter import dygraph_step

            params = parameter_list or self._parameter_list
            if params is None:
                raise ValueError(
                    "dygraph minimize() needs parameter_list (pass "
                    "model.parameters() to the optimizer)"
                )
            dygraph_step(self, list(params))
            return [], []
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        # always anchor optimizer/LR ops to the loss's own program — the
        # default program may be a different one (reference optimizer.py
        # guards with loss.block.program in minimize)
        startup = (
            startup_program
            if startup_program is not None
            else framework.default_startup_program()
        )
        with program_guard(loss.block.program, startup):
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # subclass hooks -----------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p]},
        )


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    type = "lars_momentum"

    def __init__(
        self,
        learning_rate,
        momentum=0.9,
        lars_coeff=0.001,
        lars_weight_decay=0.0005,
        epsilon=0,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "epsilon": self._epsilon,
            },
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=(1,))
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=(1,))

    def _optimize_inputs_outputs(self, p, g):
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        inputs = {
            "Param": [p],
            "Grad": [g],
            "Moment1": [m1],
            "Moment2": [m2],
            "Beta1Pow": [b1p],
            "Beta2Pow": [b2p],
            "LearningRate": [self._learning_rate_var],
        }
        outputs = {
            "ParamOut": [p],
            "Moment1Out": [m1],
            "Moment2Out": [m2],
            "Beta1PowOut": [b1p],
            "Beta2PowOut": [b2p],
        }
        return inputs, outputs

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs, outputs = self._optimize_inputs_outputs(p, g)
        return block.append_op(
            type="adam",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )


class AdamWOptimizer(AdamOptimizer):
    type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, apply_decay_param_fun=None, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._weight_decay = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs, outputs = self._optimize_inputs_outputs(p, g)
        with_decay = True
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            with_decay = False
        return block.append_op(
            type="adamw",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "coeff": self._weight_decay,
                "with_decay": with_decay,
            },
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [m],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    """Adamax (reference optimizer.py Adamax, operators/optimizers/
    adamax_op.cc): Adam with the L-infinity norm in place of the second
    moment. The op has no Beta1PowOut slot (reference parity), so the
    beta1 power accumulator advances via a scale op in _finish_update."""

    type = "adamax"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator(
                "beta1_pow_acc", p, fill_value=self._beta1, shape=(1,)
            )

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [self._get_accumulator("moment", p)],
                "InfNorm": [self._get_accumulator("inf_norm", p)],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={
                "ParamOut": [p],
                "MomentOut": [self._get_accumulator("moment", p)],
                "InfNormOut": [self._get_accumulator("inf_norm", p)],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )

    def _finish_update(self, block, params_grads):
        for p, g in params_grads:
            if g is None:
                continue
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                type="scale",
                inputs={"X": [b1p]},
                outputs={"Out": [b1p]},
                attrs={"scale": self._beta1, "bias": 0.0,
                       "bias_after_scale": True},
            )


class DecayedAdagradOptimizer(Optimizer):
    """Decayed Adagrad (reference optimizer.py DecayedAdagrad,
    operators/optimizers/decayed_adagrad_op.cc): adagrad whose squared-
    gradient accumulator decays by `decay` each step."""

    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [self._get_accumulator("moment", p)],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={
                "ParamOut": [p],
                "MomentOut": [self._get_accumulator("moment", p)],
            },
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(
        self,
        learning_rate,
        rho=0.95,
        epsilon=1e-6,
        momentum=0.0,
        centered=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum_acc", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs = {
            "Param": [p],
            "Grad": [g],
            "MeanSquare": [self._get_accumulator("mean_square", p)],
            "Moment": [self._get_accumulator("momentum_acc", p)],
            "LearningRate": [self._learning_rate_var],
        }
        outputs = {
            "ParamOut": [p],
            "MeanSquareOut": [self._get_accumulator("mean_square", p)],
            "MomentOut": [self._get_accumulator("momentum_acc", p)],
        }
        if self._centered:
            inputs["MeanGrad"] = [self._get_accumulator("mean_grad", p)]
            outputs["MeanGradOut"] = [self._get_accumulator("mean_grad", p)]
        return block.append_op(
            type="rmsprop",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class LambOptimizer(AdamOptimizer):
    type = "lamb"

    def __init__(
        self,
        learning_rate=0.001,
        lamb_weight_decay=0.01,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-6,
        exclude_from_weight_decay_fn=None,
        **kwargs,
    ):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs, outputs = self._optimize_inputs_outputs(p, g)
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        return block.append_op(
            type="lamb",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "weight_decay": wd,
            },
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [p],
                "Grad": [g],
                "SquaredAccumulator": [self._get_accumulator("squared", p)],
                "LinearAccumulator": [self._get_accumulator("linear", p)],
                "LearningRate": [self._learning_rate_var],
            },
            outputs={
                "ParamOut": [p],
                "SquaredAccumOut": [self._get_accumulator("squared", p)],
                "LinearAccumOut": [self._get_accumulator("linear", p)],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class DpsgdOptimizer(Optimizer):
    type = "dpsgd"

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="dpsgd",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [self._learning_rate_var]},
            outputs={"ParamOut": [p]},
            attrs={
                "clip": self._clip,
                "batch_size": self._batch_size,
                "sigma": self._sigma,
            },
        )


# ---------------------------------------------------------------------------
# meta-optimizers: wrappers that rewrite the program around an inner optimizer
# (reference optimizer.py:3627-5171). On TPU all of them are branchless
# program rewrites — conditional updates use `where` selects instead of the
# reference's conditional_block op, so the step stays a single XLA program.
# ---------------------------------------------------------------------------


def _create_persistable_var(name, shape, dtype, fill_value=0.0):
    """Main-program persistable var + zero/constant startup init (the
    pattern of Optimizer._add_accumulator)."""
    main_block = framework.default_main_program().global_block()
    if name in main_block.vars:
        return main_block.vars[name]
    v = main_block.create_var(
        name=name, shape=tuple(shape), dtype=dtype, persistable=True,
        stop_gradient=True,
    )
    startup_block = framework.default_startup_program().global_block()
    sv = startup_block.create_var(
        name=name, shape=tuple(shape), dtype=dtype, persistable=True
    )
    ConstantInitializer(float(fill_value))(sv, startup_block)
    return v


def _append_step_cond(block, counter_name, k):
    """Emit: counter += 1; cond = (counter % k == 0). Returns the bool
    cond var (shape (1,)). int32 counter: exact to 2^31 steps (a float32
    one saturates at 2^24 and would freeze the boundary forever; int64
    would be silently truncated to int32 anyway with x64 disabled)."""
    step = _create_persistable_var(counter_name, (1,), "int32", 0.0)
    block.append_op(
        type="increment", inputs={"X": [step]}, outputs={"Out": [step]},
        attrs={"step": 1.0},
    )
    k_name = unique_name.generate(counter_name + "_k")
    block.append_op(
        type="fill_constant", outputs={"Out": [k_name]},
        attrs={"shape": [1], "dtype": "int32", "value": float(k)},
    )
    mod_name = unique_name.generate(counter_name + "_mod")
    block.append_op(
        type="elementwise_mod", inputs={"X": [step], "Y": [k_name]},
        outputs={"Out": [mod_name]},
    )
    zero_name = unique_name.generate(counter_name + "_zero")
    block.append_op(
        type="fill_constant", outputs={"Out": [zero_name]},
        attrs={"shape": [1], "dtype": "int32", "value": 0.0},
    )
    cond_name = unique_name.generate(counter_name + "_cond")
    block.append_op(
        type="equal", inputs={"X": [mod_name], "Y": [zero_name]},
        outputs={"Out": [cond_name]},
    )
    return block.var(cond_name)


def _mask_region(block, cond, start_idx):
    """Make the persistable-state writes of ops[start_idx:] conditional on
    `cond`: snapshot each written persistable var before the region, then
    select(cond, new, old) after it. Branchless equivalent of running the
    region inside the reference's conditional_block
    (operators/controlflow/conditional_block_op.cc)."""
    region = list(block.ops[start_idx:])
    written = []
    for op in region:
        for n in op.output_names():
            v = block._find_var_recursive(n)
            if v is not None and v.persistable and n not in written:
                written.append(n)
    for i, n in enumerate(written):
        block._insert_op(
            start_idx + i,
            type="assign",
            inputs={"X": [n]},
            outputs={"Out": [n + "@MASK_OLD"]},
        )
    for n in written:
        block.append_op(
            type="where",
            inputs={"Condition": [cond], "X": [n], "Y": [n + "@MASK_OLD"]},
            outputs={"Out": [n]},
        )


class GradientMergeOptimizer:
    """Accumulate grads over k_steps microbatches, apply the inner update
    on the k-th (reference optimizer.py:4948). The inner optimizer's update
    ops run every step but their persistable-state writes are masked by a
    (step % k == 0) select, so parameters and moments only change on the
    boundary step — one compiled program, no control-flow divergence."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if framework.in_dygraph_mode():
            raise RuntimeError("GradientMergeOptimizer is static-graph only")
        params_grads = self.inner_opt.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        main = loss.block.program
        startup = (
            startup_program
            if startup_program is not None
            else framework.default_startup_program()
        )
        with program_guard(main, startup):
            block = main.global_block()
            cond = _append_step_cond(
                block, unique_name.generate("gradient_merge_step"), self.k_steps
            )
            merged = []
            for p, g in params_grads:
                if g is None:
                    continue
                acc = _create_persistable_var(
                    p.name + "@GradientMerge", p.shape, p.dtype, 0.0
                )
                block.append_op(
                    type="elementwise_add",
                    inputs={"X": [acc], "Y": [g]},
                    outputs={"Out": [acc]},
                )
                if self.avg:
                    avg_name = acc.name + "@AVG"
                    block.append_op(
                        type="scale",
                        inputs={"X": [acc]},
                        outputs={"Out": [avg_name]},
                        attrs={"scale": 1.0 / self.k_steps, "bias": 0.0},
                    )
                    merged.append((p, block.var(avg_name)))
                else:
                    merged.append((p, acc))
            start_idx = len(block.ops)
            optimize_ops = self.inner_opt.apply_optimize(loss, startup, merged)
            _mask_region(block, cond, start_idx)
            # reset accumulators on the boundary step
            for p, g in params_grads:
                if g is None:
                    continue
                acc_name = p.name + "@GradientMerge"
                z = unique_name.generate(acc_name + "_zero")
                block.append_op(
                    type="fill_zeros_like",
                    inputs={"X": [acc_name]},
                    outputs={"Out": [z]},
                )
                block.append_op(
                    type="where",
                    inputs={"Condition": [cond], "X": [z], "Y": [acc_name]},
                    outputs={"Out": [acc_name]},
                )
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


class LookaheadOptimizer:
    """Lookahead (k steps forward, 1 step back; reference optimizer.py:4787):
    the fast (inner) optimizer steps every iteration; every k steps the slow
    weights move toward the fast ones and the fast weights are reset."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert 0.0 <= alpha <= 1.0
        self.inner_opt = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if framework.in_dygraph_mode():
            raise RuntimeError("LookaheadOptimizer is static-graph only")
        optimize_ops, params_grads = self.inner_opt.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set,
        )
        main = loss.block.program
        startup = (
            startup_program
            if startup_program is not None
            else framework.default_startup_program()
        )
        with program_guard(main, startup):
            block = main.global_block()
            cond = _append_step_cond(
                block, unique_name.generate("lookahead_step"), self.k
            )
            for p, _ in params_grads:
                slow_name = p.name + "@SLOW"
                _create_persistable_var(slow_name, p.shape, p.dtype, 0.0)
                # slow weights start as a copy of the initialized params
                sblock = framework.default_startup_program().global_block()
                sblock.append_op(
                    type="assign",
                    inputs={"X": [p.name]},
                    outputs={"Out": [slow_name]},
                )
                diff = unique_name.generate(p.name + "_la_diff")
                block.append_op(
                    type="elementwise_sub",
                    inputs={"X": [p.name], "Y": [slow_name]},
                    outputs={"Out": [diff]},
                )
                scaled = unique_name.generate(p.name + "_la_scaled")
                block.append_op(
                    type="scale",
                    inputs={"X": [diff]},
                    outputs={"Out": [scaled]},
                    attrs={"scale": self.alpha, "bias": 0.0},
                )
                new_slow = unique_name.generate(p.name + "_la_new_slow")
                block.append_op(
                    type="elementwise_add",
                    inputs={"X": [slow_name], "Y": [scaled]},
                    outputs={"Out": [new_slow]},
                )
                for target in (slow_name, p.name):
                    block.append_op(
                        type="where",
                        inputs={"Condition": [cond], "X": [new_slow], "Y": [target]},
                        outputs={"Out": [target]},
                    )
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


class RecomputeOptimizer:
    """Activation recompute between user-marked checkpoints (reference
    optimizer.py:4478 + backward.py:629). See ops/recompute.py for the
    TPU-native mechanism: each segment between checkpoints is fused into a
    `recompute_segment` op replayed under jax.checkpoint, so XLA stores only
    the checkpoint tensors across forward->backward and rematerializes the
    rest inside the grad op. Intermediates inside a segment can no longer be
    fetched (same observable contract as the reference's recompute)."""

    def __init__(self, optimizer):
        self.inner_opt = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = [
            c.name if isinstance(c, framework.Variable) else str(c)
            for c in (checkpoints or [])
        ]

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        if not self._checkpoints:
            raise ValueError("RecomputeOptimizer needs _set_checkpoints(...)")
        _fuse_recompute_segments(loss, self._checkpoints)
        return self.inner_opt.backward(
            loss, startup_program, parameter_list, no_grad_set, callbacks
        )

    def apply_gradients(self, params_grads):
        return self.inner_opt.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.inner_opt.apply_optimize(loss, startup_program, params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if framework.in_dygraph_mode():
            raise RuntimeError("RecomputeOptimizer is static-graph only")
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        optimize_ops = self.inner_opt.apply_optimize(
            loss,
            startup_program
            if startup_program is not None
            else framework.default_startup_program(),
            params_grads,
        )
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


def _fuse_recompute_segments(loss, checkpoint_names):
    """Split the forward region of loss's block at checkpoint-producing ops
    and collapse each multi-op segment into one `recompute_segment` op."""
    block = loss.block
    ckpts = set(checkpoint_names)
    loss_idx = None
    for i in reversed(range(len(block.ops))):
        if loss.name in block.ops[i].output_names():
            loss_idx = i
            break
    if loss_idx is None:
        raise ValueError(f"loss var {loss.name!r} is not produced by any op")
    fwd_ops = block.ops[: loss_idx + 1]
    tail_ops = block.ops[loss_idx + 1:]

    segments, cur = [], []
    for op in fwd_ops:
        cur.append(op)
        if any(n in ckpts for n in op.output_names()):
            segments.append(cur)
            cur = []
    if cur:
        segments.append(cur)

    new_ops = []
    for si, seg in enumerate(segments):
        if len(seg) < 2:
            new_ops.extend(seg)
            continue
        produced = []
        for op in seg:
            for n in op.output_names():
                if n not in produced:
                    produced.append(n)
        in_names = []
        seen_out = set()
        for op in seg:
            for n in op.input_names():
                if n not in seen_out and n not in in_names:
                    in_names.append(n)
            seen_out.update(op.output_names())
        # names still observable after the segment: later forward reads,
        # checkpoints, persistables (bn running stats), and the loss
        consumed_later = set()
        for later_seg in segments[si + 1:]:
            for op in later_seg:
                consumed_later.update(op.input_names())
        for op in tail_ops:
            consumed_later.update(op.input_names())
        out_names = []
        for n in produced:
            v = block._find_var_recursive(n)
            if (
                n in consumed_later
                or n in ckpts
                or n == loss.name
                or (v is not None and v.persistable)
            ):
                out_names.append(n)
        if not out_names:
            out_names = [produced[-1]]
        out_metas = []
        for n in out_names:
            v = block._find_var_recursive(n)
            out_metas.append((v.shape, v.dtype))
        # in_names was collected before each op's own outputs were marked
        # produced, so every entry is an external read — including vars the
        # segment reads then overwrites in place (batch_norm Mean/MeanOut
        # share one name); those must stay inputs AND outputs.
        fused = framework.Operator(
            block,
            "recompute_segment",
            inputs={"X": in_names},
            outputs={"Out": out_names},
            attrs={
                "recompute_sub_ops": seg,
                "recompute_in_names": in_names,
                "recompute_out_names": out_names,
                "recompute_out_metas": out_metas,
                "recompute_seg_salt": 0x7EC0 + si,
            },
        )
        for n in out_names:
            v = block._find_var_recursive(n)
            if v is not None:
                v.op = fused
        new_ops.append(fused)
    block.ops = new_ops + tail_ops
    block.program._bump_version()


class PipelineOptimizer:
    """Pipeline-parallel training (reference optimizer.py:3627 +
    PipelineTrainer/SectionWorker, framework/section_worker.cc:82).

    TPU-native design: the reference splits the program into per-device
    sections and streams microbatches through them on threads connected by
    concurrent queues. Here the pipeline is expressed INSIDE the compiled
    step: scan-based encoder stacks (`fused_encoder_stack`) get a GPipe
    schedule over the "pp" mesh axis (layer-dim-sharded params, microbatch
    activations rotating via ppermute — ops/encoder_stack.py:_gpipe_stack),
    and the whole fwd+bwd+update remains one differentiable XLA program.
    `device_guard` stage tags (attr "op_device") are accepted for program
    parity; ops carrying them run co-scheduled by XLA — with SPMD there is
    no benefit to thread-level sections, the pp axis carries the
    parallelism."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self.inner_opt = optimizer
        self._num_microbatches = int(num_microbatches)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if framework.in_dygraph_mode():
            raise RuntimeError("PipelineOptimizer is static-graph only")
        program = loss.block.program
        # mark pipeline-able ops BEFORE backward so grad ops capture attrs
        for block in program.blocks:
            for op in block.ops:
                if op.type == "fused_encoder_stack":
                    op._set_attr("pipeline", True)
                    op._set_attr("num_microbatches", self._num_microbatches)
        self._stage_ops = self._collect_stages(program)
        if len(self._stage_ops) > 1:
            # the single-program lowering co-schedules every stage in one
            # XLA computation: multi-stage device_guard tags describe a
            # partition it does NOT perform. Raise (no-silently-ignored-
            # flags rule) unless the fallback is explicitly requested.
            from .flags import flag

            stages = ", ".join(sorted(self._stage_ops))
            if flag("FLAGS_pipeline_single_program_fallback"):
                import warnings

                warnings.warn(
                    f"PipelineOptimizer: device_guard names {len(self._stage_ops)} "
                    f"stages ({stages}); running them co-scheduled in ONE "
                    f"compiled program (FLAGS_pipeline_single_program_fallback=1). "
                    f"Stage placement is not performed — use the 'pp' mesh "
                    f"axis with fused_encoder_stack for real pipeline "
                    f"parallelism.",
                    stacklevel=2,
                )
            else:
                raise RuntimeError(
                    f"PipelineOptimizer: this program tags ops with "
                    f"{len(self._stage_ops)} device_guard stages ({stages}), "
                    f"but the TPU lowering compiles ONE program and performs "
                    f"no stage placement — the tags would be silently "
                    f"ignored. Use the 'pp' mesh axis (fused_encoder_stack "
                    f"GPipe schedule) for pipeline parallelism, or set "
                    f"FLAGS_pipeline_single_program_fallback=1 to accept "
                    f"co-scheduled single-program execution."
                )
        return self.inner_opt.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set,
        )

    @staticmethod
    def _collect_stages(program):
        """Group ops by device_guard tag (diagnostics/parity)."""
        stages = {}
        for block in program.blocks:
            for op in block.ops:
                dev = op.attr("op_device")
                if dev is not None:
                    stages.setdefault(dev, []).append(op)
        return stages

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference optimizer.py:3381).

    update() appends the in-graph accumulation ops (call after
    optimizer.minimize); apply()/restore() swap scope values host-side
    (checkpointed persistables stay by-name compatible).

    thres_steps (reference :3413): a Variable scheduling the decay as
    min(decay, (1+thres_steps)/(10+thres_steps)). The zero-init bias is
    corrected at apply() by 1 - prod(decay_t) — for constant decay that is
    exactly the reference's 1 - decay^t factor, and it stays exact under
    scheduling (where a decay^t correction would not)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._name = name or ""
        self._pairs = []  # (param_name, ema_name)
        self._step_name = unique_name.generate(self._name + "@EMA@step")
        self._decay_pow_name = unique_name.generate(self._name + "@EMA@decay_pow")
        self._backup = {}

    def _append_decay_var(self, block):
        """Emit the per-step effective decay var (shape (1,) float32)."""
        if self._thres_steps is None:
            name = unique_name.generate(self._name + "@EMA@decay")
            block.append_op(
                type="fill_constant", outputs={"Out": [name]},
                attrs={"shape": [1], "dtype": "float32", "value": self._decay},
            )
            return name
        thres = self._thres_steps
        tname = thres.name if isinstance(thres, Variable) else str(thres)
        tf = unique_name.generate(tname + "_f")
        block.append_op(
            type="cast", inputs={"X": [tname]}, outputs={"Out": [tf]},
            attrs={"out_dtype": "float32"},
        )
        num = unique_name.generate(tname + "_num")
        block.append_op(
            type="scale", inputs={"X": [tf]}, outputs={"Out": [num]},
            attrs={"scale": 1.0, "bias": 1.0},
        )
        den = unique_name.generate(tname + "_den")
        block.append_op(
            type="scale", inputs={"X": [tf]}, outputs={"Out": [den]},
            attrs={"scale": 1.0, "bias": 10.0},
        )
        ramp = unique_name.generate(tname + "_ramp")
        block.append_op(
            type="elementwise_div", inputs={"X": [num], "Y": [den]},
            outputs={"Out": [ramp]},
        )
        dconst = unique_name.generate(tname + "_dconst")
        block.append_op(
            type="fill_constant", outputs={"Out": [dconst]},
            attrs={"shape": [1], "dtype": "float32", "value": self._decay},
        )
        name = unique_name.generate(self._name + "@EMA@decay")
        block.append_op(
            type="elementwise_min", inputs={"X": [dconst], "Y": [ramp]},
            outputs={"Out": [name]},
        )
        return name

    def update(self):
        main = framework.default_main_program()
        block = main.global_block()
        step = _create_persistable_var(self._step_name, (1,), "int32", 0.0)
        block.append_op(
            type="increment", inputs={"X": [step]}, outputs={"Out": [step]},
            attrs={"step": 1.0},
        )
        decay_name = self._append_decay_var(block)
        one_minus = unique_name.generate(decay_name + "_om")
        block.append_op(
            type="scale", inputs={"X": [decay_name]}, outputs={"Out": [one_minus]},
            attrs={"scale": -1.0, "bias": 1.0},
        )
        # running prod of effective decays (debias denominator at apply)
        _create_persistable_var(self._decay_pow_name, (1,), "float32", 1.0)
        block.append_op(
            type="elementwise_mul",
            inputs={"X": [self._decay_pow_name], "Y": [decay_name]},
            outputs={"Out": [self._decay_pow_name]},
        )
        for p in main.all_parameters():
            if not p.trainable:
                continue
            ema_name = p.name + "@EMA" + self._name
            _create_persistable_var(ema_name, p.shape, p.dtype, 0.0)
            t1 = unique_name.generate(ema_name + "_t1")
            block.append_op(
                type="elementwise_mul", inputs={"X": [ema_name], "Y": [decay_name]},
                outputs={"Out": [t1]},
            )
            t2 = unique_name.generate(ema_name + "_t2")
            block.append_op(
                type="elementwise_mul", inputs={"X": [p.name], "Y": [one_minus]},
                outputs={"Out": [t2]},
            )
            block.append_op(
                type="elementwise_add", inputs={"X": [t1], "Y": [t2]},
                outputs={"Out": [ema_name]},
            )
            if (p.name, ema_name) not in self._pairs:
                # update() may be called more than once (reference allows
                # re-issuing the update ops); duplicated pairs would make
                # apply() back up an already-swapped value and restore()
                # leave EMA weights in the parameters permanently
                self._pairs.append((p.name, ema_name))

    def apply(self, executor=None, need_restore=True):
        """Context manager: swap params for debiased EMA values in scope."""
        import contextlib

        from .executor import global_scope

        @contextlib.contextmanager
        def _guard():
            scope = global_scope()
            decay_pow = float(np.asarray(scope.find_var(self._decay_pow_name))[0])
            debias = max(1.0 - decay_pow, 1e-12)
            self._backup = {}
            for pname, ename in self._pairs:
                self._backup.setdefault(pname, scope.find_var(pname))
                ema = np.asarray(scope.find_var(ename))
                scope.set_var(pname, (ema / debias).astype(ema.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return _guard()

    def restore(self, executor=None):
        from .executor import global_scope

        scope = global_scope()
        for pname, val in self._backup.items():
            scope.set_var(pname, val)
        self._backup = {}


class ModelAverage:
    """Running average of parameters over a trailing window (reference
    optimizer.py:3068). Window rule (reference :3091): restart when
    num_accumulates >= min_average_window AND
    num_accumulates >= min(max_average_window, num_updates*average_window_rate).
    The reference rotates sum_1/sum_2/sum_3 buffers; here a single
    (sum, count) pair restarts from the current parameter — same
    averaged-weights contract."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._pairs = []  # (param, sum_name, num_name)
        self._backup = {}
        main = framework.default_main_program()
        block = main.global_block()

        num_upd = _create_persistable_var(
            unique_name.generate("@MA@num_updates"), (1,), "int32", 0.0
        )
        block.append_op(
            type="increment", inputs={"X": [num_upd]}, outputs={"Out": [num_upd]},
            attrs={"step": 1.0},
        )
        updf = unique_name.generate("@MA@num_updates_f")
        block.append_op(
            type="cast", inputs={"X": [num_upd]}, outputs={"Out": [updf]},
            attrs={"out_dtype": "float32"},
        )
        ratew = unique_name.generate("@MA@rate_window")
        block.append_op(
            type="scale", inputs={"X": [updf]}, outputs={"Out": [ratew]},
            attrs={"scale": self.average_window, "bias": 0.0},
        )
        maxw = unique_name.generate("@MA@maxw")
        block.append_op(
            type="fill_constant", outputs={"Out": [maxw]},
            attrs={"shape": [1], "dtype": "float32",
                   "value": float(self.max_average_window)},
        )
        window = unique_name.generate("@MA@window")
        block.append_op(
            type="elementwise_min", inputs={"X": [maxw], "Y": [ratew]},
            outputs={"Out": [window]},
        )
        minw = unique_name.generate("@MA@minw")
        block.append_op(
            type="fill_constant", outputs={"Out": [minw]},
            attrs={"shape": [1], "dtype": "float32",
                   "value": float(self.min_average_window)},
        )

        for p in main.all_parameters():
            if not p.trainable:
                continue
            sum_name = p.name + "@MA_SUM"
            num_name = p.name + "@MA_NUM"
            _create_persistable_var(sum_name, p.shape, p.dtype, 0.0)
            _create_persistable_var(num_name, (1,), "float32", 0.0)
            ge_min = unique_name.generate(num_name + "_ge_min")
            block.append_op(
                type="greater_equal", inputs={"X": [num_name], "Y": [minw]},
                outputs={"Out": [ge_min]},
            )
            ge_win = unique_name.generate(num_name + "_ge_win")
            block.append_op(
                type="greater_equal", inputs={"X": [num_name], "Y": [window]},
                outputs={"Out": [ge_win]},
            )
            restart = unique_name.generate(num_name + "_restart")
            block.append_op(
                type="logical_and", inputs={"X": [ge_min], "Y": [ge_win]},
                outputs={"Out": [restart]},
            )
            acc = unique_name.generate(sum_name + "_acc")
            block.append_op(
                type="elementwise_add", inputs={"X": [sum_name], "Y": [p.name]},
                outputs={"Out": [acc]},
            )
            block.append_op(
                type="where",
                inputs={"Condition": [restart], "X": [p.name], "Y": [acc]},
                outputs={"Out": [sum_name]},
            )
            bumped = unique_name.generate(num_name + "_bump")
            block.append_op(
                type="increment", inputs={"X": [num_name]},
                outputs={"Out": [bumped]}, attrs={"step": 1.0},
            )
            one = unique_name.generate(num_name + "_one")
            block.append_op(
                type="fill_constant", outputs={"Out": [one]},
                attrs={"shape": [1], "dtype": "float32", "value": 1.0},
            )
            block.append_op(
                type="where",
                inputs={"Condition": [restart], "X": [one], "Y": [bumped]},
                outputs={"Out": [num_name]},
            )
            self._pairs.append((p.name, sum_name, num_name))

    def apply(self, executor=None, need_restore=True):
        import contextlib

        from .executor import global_scope

        @contextlib.contextmanager
        def _guard():
            scope = global_scope()
            self._backup = {}
            for pname, sname, nname in self._pairs:
                self._backup[pname] = scope.find_var(pname)
                s = np.asarray(scope.find_var(sname))
                n = float(np.asarray(scope.find_var(nname))[0])
                if n > 0:
                    scope.set_var(pname, (s / n).astype(s.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return _guard()

    def restore(self, executor=None):
        from .executor import global_scope

        scope = global_scope()
        for pname, val in self._backup.items():
            scope.set_var(pname, val)
        self._backup = {}


# paddle-style short aliases (fluid.optimizer.SGD etc.)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adagrad = AdagradOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer
Ftrl = FtrlOptimizer
Dpsgd = DpsgdOptimizer
LarsMomentum = LarsMomentumOptimizer
