"""Dataset API over the native data feed.

Parity surface: /root/reference/python/paddle/fluid/dataset.py
(DatasetFactory:22, InMemoryDataset:328 with load_into_memory:611 and
global_shuffle:684, QueueDataset:852), backed in the reference by the C++
Dataset/DataFeed (framework/data_set.h, data_feed.h). Here the backend is
paddle_tpu/native/datafeed.cc (reader threads -> channel -> batches) with
a pure-Python fallback.

Records are text lines of whitespace-separated floats; set_use_var
declares the per-sample schema — each row is the concatenation of the
flattened vars in order (the dense MultiSlot layout)."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from . import framework


class DatasetFactory:
    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist: List[str] = []
        self._use_vars: List[framework.Variable] = []
        self._seed = 0
        self._shuffle_buffer = 0
        self._feed = None

    # -- reference surface -------------------------------------------------
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)
        self._feed = None

    def set_use_var(self, var_list):
        self._use_vars = list(var_list)

    def set_pipe_command(self, cmd):  # parity stub: no pipe preprocessing
        self._pipe_command = cmd

    # -- schema ------------------------------------------------------------
    def _widths(self):
        ws = []
        for v in self._use_vars:
            shape = [d for d in (v.shape or (1,)) if d != -1]
            ws.append(int(np.prod(shape)) if shape else 1)
        return ws

    def _ncols(self):
        return sum(self._widths())

    def _make_feed(self, shuffle_buffer=0):
        from ..native import make_datafeed

        return make_datafeed(
            self._ncols(), self._batch_size,
            shuffle_buffer=shuffle_buffer, seed=self._seed,
            num_threads=self._thread_num,
        )

    def _split_batch(self, rows: np.ndarray):
        """rows [n, ncols] -> feed dict keyed by use_var names."""
        out = {}
        off = 0
        n = rows.shape[0]
        for v, w in zip(self._use_vars, self._widths()):
            chunk = rows[:, off:off + w]
            off += w
            shape = [d for d in (v.shape or ()) if d != -1]
            arr = chunk.reshape((n, *shape)) if shape else chunk.reshape(n)
            if v.dtype is not None and arr.dtype != v.dtype:
                arr = arr.astype(v.dtype)
            out[v.name] = arr
        return out

    def _as_loader(self, drop_last=True):
        feed = self._iter_feed()
        for rows in feed:
            if drop_last and rows.shape[0] < self._batch_size:
                continue
            yield self._split_batch(rows)

    def _iter_feed(self):
        raise NotImplementedError


class QueueDataset(DatasetBase):
    """Streaming mode (reference dataset.py:852): reader threads feed the
    channel; batches stream out without landing in host memory."""

    def _iter_feed(self):
        feed = self._make_feed(shuffle_buffer=self._shuffle_buffer)
        feed.set_filelist(self._filelist)
        return iter(feed)

    def local_shuffle(self, buffer_size: int = 1024):
        self._shuffle_buffer = int(buffer_size)


class InMemoryDataset(DatasetBase):
    """Out-of-core -> in-memory mode (reference dataset.py:328)."""

    def __init__(self):
        super().__init__()
        self._loaded = None

    def load_into_memory(self):
        self._loaded = self._make_feed()
        self._loaded.set_filelist(self._filelist)
        self._loaded.load_into_memory()

    def local_shuffle(self):
        self._require_loaded()
        self._loaded.shuffle()

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-host build: all data is already local, so global == local
        (the reference shuffles across trainers via the PS; multi-host
        sharding belongs to each host's filelist split)."""
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None) -> int:
        self._require_loaded()
        return self._loaded.memory_size()

    def release_memory(self):
        self._loaded = None

    def _require_loaded(self):
        if self._loaded is None:
            raise RuntimeError("call load_into_memory() first")

    def _iter_feed(self):
        self._require_loaded()
        self._loaded.rewind()
        return iter(self._loaded)
