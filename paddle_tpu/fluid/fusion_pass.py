"""Graph-level operator fusion passes over a Program.

Sibling of the AMP rewriter (contrib/mixed_precision/fp16_utils.py): a
pass walks a block's op list, pattern-matches, and rewrites in place
BEFORE append_backward runs, so the synthesized grad ops differentiate
the fused op directly (its emitter carries the custom-VJP Pallas
backward — ops/pallas/conv_bn.py).

conv+BN fusion (FLAGS_conv_bn_fusion): rewrites

    conv2d -> batch_norm [-> relu]

triples into one `fused_conv_bn` op when the intermediate activations
have no other consumer. The rewrite is semantics-preserving op-for-op:
the fused emitter reproduces the exact math of the unfused chain (f32
one-pass moments, running-stat update, relu), so with the flag off the
program — and with it the whole compiled step — is bit-identical to the
unfused baseline. Patterns the kernel cannot cover (grouped or dilated
convs, mismatched layouts, shared intermediates) are left untouched;
`is_test` BNs ARE rewritten — the emitter folds them into the conv
weights (one conv + bias add, no normalization pass).
"""
from __future__ import annotations

from typing import List

from . import framework
from .flags import flag


def _consumer_indices(block, name: str) -> List[int]:
    return [
        idx for idx, op in enumerate(block.ops) if name in op.input_names()
    ]


def _fusable_conv(op) -> bool:
    if op.type != "conv2d":
        return False
    if int(op.attr("groups", 1)) != 1:
        return False
    if tuple(op.attr("dilations", [1, 1])) != (1, 1):
        return False
    return True


def _exclusive_intermediate(block, name: str, consumer_idx: int) -> bool:
    """True when `name` is a plain SSA temporary read only by ops[consumer_idx]."""
    v = block._find_var_recursive(name)
    if v is None or v.persistable or v.is_data:
        return False
    return _consumer_indices(block, name) == [consumer_idx]


def _try_fuse_at(block, i) -> bool:
    conv = block.ops[i]
    if not _fusable_conv(conv):
        return False
    conv_out = conv.output("Output")
    if len(conv_out) != 1:
        return False
    conv_out = conv_out[0]
    users = _consumer_indices(block, conv_out)
    if len(users) != 1:
        return False
    j = users[0]
    bn = block.ops[j]
    if bn.type != "batch_norm" or bn.input("X") != [conv_out]:
        return False
    if not _exclusive_intermediate(block, conv_out, j):
        return False
    if bn.attr("data_layout", "NCHW") != conv.attr("data_format", "NCHW"):
        return False

    y = bn.output("Y")[0]
    relu_idx = None
    out_name = y
    yusers = _consumer_indices(block, y)
    if (
        len(yusers) == 1
        and block.ops[yusers[0]].type == "relu"
        and block.ops[yusers[0]].input("X") == [y]
        and _exclusive_intermediate(block, y, yusers[0])
    ):
        relu_idx = yusers[0]
        out_name = block.ops[relu_idx].output("Out")[0]

    attrs = {
        "strides": list(conv.attr("strides", [1, 1])),
        "paddings": list(conv.attr("paddings", [0, 0])),
        "dilations": list(conv.attr("dilations", [1, 1])),
        "groups": int(conv.attr("groups", 1)),
        "padding_algorithm": conv.attr("padding_algorithm", "EXPLICIT"),
        "data_format": conv.attr("data_format", "NCHW"),
        "epsilon": bn.attr("epsilon", 1e-5),
        "momentum": bn.attr("momentum", 0.9),
        "is_test": bn.attr("is_test", False),
        "use_global_stats": bn.attr("use_global_stats", False),
        "with_relu": relu_idx is not None,
    }
    dev = conv.attr("op_device")
    if dev is not None:
        attrs["op_device"] = dev
    cs = conv.attr(framework.OP_CALLSTACK_ATTR)
    if cs is not None:
        # diagnostics on the fused op point at the user's conv call
        attrs[framework.OP_CALLSTACK_ATTR] = cs

    fused = framework.Operator(
        block,
        "fused_conv_bn",
        inputs={
            "Input": list(conv.input("Input")),
            "Filter": list(conv.input("Filter")),
            "Scale": list(bn.input("Scale")),
            "Bias": list(bn.input("Bias")),
            "Mean": list(bn.input("Mean")),
            "Variance": list(bn.input("Variance")),
        },
        outputs={
            "Y": [out_name],
            "MeanOut": list(bn.output("MeanOut")),
            "VarianceOut": list(bn.output("VarianceOut")),
            "SavedMean": list(bn.output("SavedMean")),
            "SavedVariance": list(bn.output("SavedVariance")),
        },
        attrs=attrs,
    )
    for idx in sorted(filter(lambda k: k is not None, (i, j, relu_idx)),
                      reverse=True):
        del block.ops[idx]
    block.ops.insert(i, fused)
    for n in fused.output_names():
        v = block._find_var_recursive(n)
        if v is not None:
            v.op = fused
    # the exclusive intermediates the deleted ops produced (conv output,
    # and the BN Y when the relu folded in) now have neither producer nor
    # consumer; leaving them in block.vars kept stale Variable.op links
    # to the removed ops (proglint: stale-last-writer / unused-var)
    dead = [conv_out]
    if relu_idx is not None:
        dead.append(y)
    for n in dead:
        block.vars.pop(n, None)
    block.program._bump_version()
    return True


def apply_conv_bn_fusion(program) -> int:
    """Fuse every conv2d->batch_norm[->relu] triple in `program`.

    Returns the number of fusions performed. Unconditional (an explicit
    call states intent); the training wiring goes through
    `maybe_apply_conv_bn_fusion`, which honors FLAGS_conv_bn_fusion.

    Under FLAGS_program_verify the rewrite runs pass-sandwiched
    (fluid/analysis): the program is verified before and after, and any
    error finding the pass introduced raises attributed to it.
    """
    from .analysis import pass_sandwich

    fused = 0
    with pass_sandwich(program, "conv_bn_fusion"):
        for block in program.blocks:
            i = 0
            while i < len(block.ops):
                if _try_fuse_at(block, i):
                    fused += 1
                i += 1
    return fused


def maybe_apply_conv_bn_fusion(program) -> int:
    """Flag-gated entry used by Optimizer.backward / the AMP decorator.
    A no-op (zero rewrites, program untouched) unless FLAGS_conv_bn_fusion
    is set."""
    if not flag("FLAGS_conv_bn_fusion"):
        return 0
    return apply_conv_bn_fusion(program)
