"""In-graph learning-rate schedules.

Parity surface: /root/reference/python/paddle/fluid/layers/learning_rate_scheduler.py
(noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup).

The schedule is part of the main program: a persistable step counter is
incremented every executor run and the LR is computed from it with ops —
so the whole (step, lr, update) pipeline stays inside ONE compiled XLA
program, matching the reference's design where decay ops live in the
program rather than in host Python.
"""
from __future__ import annotations

import math

from . import framework, unique_name
from .framework import Variable, default_main_program, default_startup_program
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from . import layers

LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter(begin: int = 0) -> Variable:
    """Persistable float32 step counter, incremented once per program run."""
    main_block = default_main_program().global_block()
    if main_block.has_var(LR_COUNTER_NAME):
        # counter already materialized in this program: reuse BOTH the var
        # and its increment op (avoid double-increment)
        return main_block.var(LR_COUNTER_NAME)
    counter = main_block.create_var(
        name=LR_COUNTER_NAME, shape=(1,), dtype="float32", persistable=True
    )
    sblock = default_startup_program().global_block()
    sv = sblock.create_var(
        name=LR_COUNTER_NAME, shape=(1,), dtype="float32", persistable=True
    )
    # increment runs before any read, so the first observed value is `begin`
    ConstantInitializer(float(begin) - 1.0)(sv, sblock)
    main_block.append_op(
        type="increment",
        inputs={"X": [counter]},
        outputs={"Out": [counter]},
        attrs={"step": 1.0},
    )
    counter.stop_gradient = True
    return counter


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = learning_rate * d_model^-0.5 * min(step^-0.5, step*warmup^-1.5)."""
    step = _decay_step_counter(begin=1)
    a = layers.pow(step, -0.5)
    b = layers.scale(step, scale=warmup_steps ** -1.5)
    lr = layers.elementwise_min(a, b)
    return layers.scale(lr, scale=float(learning_rate) * (d_model ** -0.5))


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = layers.floor(div)
    return layers.scale(
        layers.elementwise_pow(
            layers.fill_constant([1], "float32", decay_rate), div
        ),
        scale=float(learning_rate),
    )


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = layers.floor(div)
    return layers.scale(
        layers.exp(layers.scale(div, scale=-decay_rate)), scale=float(learning_rate)
    )


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = layers.floor(div)
    denom = layers.scale(div, scale=decay_rate, bias=1.0)
    return layers.elementwise_div(
        layers.fill_constant([1], "float32", float(learning_rate)), denom
    )


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        ratio = layers.scale(step, scale=1.0 / decay_steps)
        div_res = layers.ceil(ratio)
        # avoid zero: when step == 0, use 1
        zero = layers.fill_constant([1], "float32", 0.0)
        one = layers.fill_constant([1], "float32", 1.0)
        div_res = layers.elementwise_max(div_res, one)
        decay_steps_var = layers.scale(div_res, scale=float(decay_steps))
        frac = layers.elementwise_div(step, decay_steps_var)
    else:
        mx = layers.fill_constant([1], "float32", float(decay_steps))
        capped = layers.elementwise_min(step, mx)
        frac = layers.scale(capped, scale=1.0 / decay_steps)
    one_minus = layers.scale(frac, scale=-1.0, bias=1.0)
    poly = layers.pow(one_minus, power)
    return layers.scale(poly, scale=float(learning_rate) - end_learning_rate, bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    """values[i] for step in (boundaries[i-1], boundaries[i]]. Implemented
    branch-free (masked sum) — XLA-friendly, no control flow."""
    assert len(values) == len(boundaries) + 1
    step = _decay_step_counter()
    lr = layers.fill_constant([1], "float32", float(values[0]))
    for i, b in enumerate(boundaries):
        bound = layers.fill_constant([1], "float32", float(b))
        past = layers.cast(layers.less_than(bound, step), "float32")  # step > b
        # lr = past ? values[i+1] : lr
        lr = layers.elementwise_add(
            layers.elementwise_mul(past, layers.fill_constant([1], "float32", float(values[i + 1]))),
            layers.elementwise_mul(layers.scale(past, scale=-1.0, bias=1.0), lr),
        )
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = layers.floor(layers.scale(step, scale=1.0 / step_each_epoch))
    cos_arg = layers.scale(epoch, scale=math.pi / epochs)
    return layers.scale(
        layers.cos(cos_arg), scale=0.5 * float(learning_rate), bias=0.5 * float(learning_rate)
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp start_lr -> end_lr over warmup_steps, then the wrapped
    schedule (variable or float)."""
    step = _decay_step_counter()
    if not isinstance(learning_rate, Variable):
        learning_rate = layers.fill_constant([1], "float32", float(learning_rate))
    warm = layers.fill_constant([1], "float32", float(warmup_steps))
    in_warmup = layers.cast(layers.less_than(step, warm), "float32")
    ramp = layers.scale(
        layers.elementwise_div(step, warm), scale=float(end_lr - start_lr), bias=float(start_lr)
    )
    return layers.elementwise_add(
        layers.elementwise_mul(in_warmup, ramp),
        layers.elementwise_mul(layers.scale(in_warmup, scale=-1.0, bias=1.0), learning_rate),
    )
