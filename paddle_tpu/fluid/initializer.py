"""Parameter initializers. Parity surface: python/paddle/fluid/initializer.py
(ConstantInitializer, UniformInitializer, NormalInitializer,
TruncatedNormalInitializer, XavierInitializer, MSRAInitializer,
NumpyArrayInitializer, BilinearInitializer). Each appends an init op to the
startup program; the Executor runs it once and the value lives in the Scope.
"""
from __future__ import annotations

import numpy as np

from .dtypes import convert_dtype, dtype_name


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = float(value)

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": self.value},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out, self.seed = fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        fan_out = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0, negative_slope=0.0, nonlinearity="relu"):
        self.uniform = uniform
        self.fan_in, self.seed = fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / fan_in))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self.value.shape),
                "dtype": var.dtype,
                "values": self.value.astype(var.dtype).flatten().tolist(),
            },
        )


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling kernels."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer needs a 4-D weight")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        idx = np.arange(size)
        w = shape[3]
        x = idx % w
        y = (idx // w) % shape[2]
        vals = (1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c))
        weight.flat[:] = vals
        return NumpyArrayInitializer(weight)(var, block)


# paddle-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
