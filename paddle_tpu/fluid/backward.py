"""Program-level reverse-mode autodiff: append_backward / calc_gradient.

Parity surface: python/paddle/fluid/backward.py (append_backward:1215,
_append_backward_ops_:862, grad accumulation via sum-op insertion:372,
recompute-aware variant:629 — see contrib/recompute).

Grad ops follow the reference's desc convention (inputs = forward inputs +
output grads, outputs = input grads named `<var>@GRAD`), but instead of ~300
hand-written GradOpMaker kernels, the default grad op `<type>_grad` is
synthesized from the forward emitter via jax.vjp (ops/registry.py). Ops with
randomness or saved residuals (dropout) register explicit grad makers.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import framework
from .dtypes import is_floating
from ..ops import registry

GRAD = framework.GRAD_VAR_SUFFIX


def _needs_grad_set(block, upto: int, parameter_list, no_grad_set) -> Set[str]:
    """Forward-propagate 'requires grad' from trainable parameters."""
    no_grad = set(no_grad_set or ())
    needs: Set[str] = set()
    for v in block.program.global_block().vars.values():
        if isinstance(v, framework.Parameter) and v.trainable and v.name not in no_grad:
            if parameter_list is None or v.name in parameter_list:
                needs.add(v.name)
    if parameter_list is not None:
        needs |= set(parameter_list)
    for op in block.ops[: upto + 1]:
        spec = registry.get(op.type)
        if spec is not None and spec.stop_gradient:
            continue
        if any(n in needs for n in op.input_names()):
            for n in op.output_names():
                v = block._find_var_recursive(n)
                if v is None or v.stop_gradient or n in no_grad:
                    continue
                if v.dtype is not None and not is_floating(v.dtype):
                    continue
                needs.add(n)
    return needs


def append_backward(
    loss: framework.Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
    checkpoints: Optional[List] = None,
) -> List[Tuple[framework.Parameter, framework.Variable]]:
    """Append grad ops for `loss` to its block; return [(param, grad_var)].

    checkpoints: list of Variables marking recompute segment boundaries
    (parity with RecomputeOptimizer's _append_backward_ops_with_checkpoints_;
    on TPU the XLA-level jax.checkpoint path in the executor is preferred,
    see contrib/recompute).

    Under FLAGS_program_verify the builder runs pass-sandwiched
    (fluid/analysis): the program is verified before and after, and any
    error finding the backward pass introduced (torn grad graph, broken
    grad metadata) raises a ProgramVerifyError attributed to it.
    """
    from .analysis import pass_sandwich

    with pass_sandwich(loss.block.program, "append_backward",
                       live_out=(loss.name,)):
        return _append_backward_impl(
            loss, parameter_list, no_grad_set, callbacks, checkpoints)


def _append_backward_impl(
    loss: framework.Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
    checkpoints: Optional[List] = None,
) -> List[Tuple[framework.Parameter, framework.Variable]]:
    if parameter_list is not None:
        parameter_list = [
            p.name if isinstance(p, framework.Variable) else p for p in parameter_list
        ]
    block = loss.block
    program = block.program

    # locate the op producing the loss
    loss_idx = None
    for i in reversed(range(len(block.ops))):
        if loss.name in block.ops[i].output_names():
            loss_idx = i
            break
    if loss_idx is None:
        raise ValueError(f"loss var {loss.name!r} is not produced by any op")

    needs = _needs_grad_set(block, loss_idx, parameter_list, no_grad_set)

    # d(loss)/d(loss) = 1
    loss_grad_name = loss.name + GRAD
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad_name]},
        attrs={
            "shape": list(loss.shape or (1,)),
            "dtype": loss.dtype,
            "value": 1.0,
        },
    )

    # partial grads per forward var (accumulated with sum ops on demand)
    partials: Dict[str, List[str]] = defaultdict(list)
    partials[loss.name].append(loss_grad_name)

    def finalize(var_name: str) -> Optional[str]:
        ps = partials.get(var_name)
        if not ps:
            return None
        if len(ps) == 1:
            return ps[0]
        out = var_name + GRAD
        block.append_op(
            type="sum", inputs={"X": list(ps)}, outputs={"Out": [out]}
        )
        partials[var_name] = [out]
        return out

    used_grad_names = {loss_grad_name}

    def new_partial_name(var_name: str) -> str:
        # unique across ALL allocations (a var feeding two slots of one op
        # must get two distinct partials, so counting partials[] alone is
        # not enough — partials are appended only after the op is emitted)
        base = var_name + GRAD
        name, i = base, 0
        while name in used_grad_names:
            i += 1
            name = f"{base}@RENAME@{i}"
        used_grad_names.add(name)
        return name

    for op in reversed(block.ops[: loss_idx + 1]):
        spec = registry.get(op.type)
        if spec is None or spec.stop_gradient:
            continue
        # finalized grads for this op's outputs
        out_grads: Dict[str, List[Optional[str]]] = {}
        any_grad = False
        for slot, names in op.outputs.items():
            gs = [finalize(n) for n in names]
            if any(g is not None for g in gs):
                out_grads[slot] = gs
                any_grad = True
        if not any_grad:
            continue
        diff_inputs = [n for n in op.input_names() if n in needs]
        if not diff_inputs:
            continue

        if spec.grad_maker is not None:
            descs, in_map = spec.grad_maker(op, {
                s: [g for g in gs if g is not None] for s, gs in out_grads.items()
            }, block)
            # Grad makers name outputs '<var>@GRAD'; if a partial with that
            # name already exists (var consumed by several ops), rename this
            # one so accumulation sums distinct values instead of duplicating.
            renames = {}
            for fwd_name, gname in in_map.items():
                uniq = new_partial_name(fwd_name)
                if uniq != gname:
                    renames[gname] = uniq
            for d in descs:
                outs = d.get("outputs") or {}
                if renames:
                    outs = {
                        s: [renames.get(n, n) for n in ns]
                        for s, ns in outs.items()
                    }
                block.append_op(
                    type=d["type"],
                    inputs=d.get("inputs"),
                    outputs=outs,
                    attrs=d.get("attrs"),
                )
            for fwd_name, gname in in_map.items():
                if fwd_name in needs:
                    partials[fwd_name].append(renames.get(gname, gname))
            continue

        # ---- generic vjp grad op ----
        if registry.get(op.type + "_grad") is None:
            raise NotImplementedError(
                f"op {op.type!r} is marked non-differentiable (no_vjp_grad) "
                f"and registers no grad maker, but a gradient flows through "
                f"it; mark the consuming path stop_gradient or add a grad "
                f"maker for {op.type!r}"
            )
        grad_inputs: Dict[str, List[str]] = {}
        for slot, names in op.inputs.items():
            grad_inputs[slot] = list(names)
        for slot, gs in out_grads.items():
            filled: List[str] = []
            for g, n in zip(gs, op.outputs[slot]):
                if g is None:
                    z = n + GRAD + "@ZERO"
                    block.append_op(
                        type="fill_zeros_like",
                        inputs={"X": [n]},
                        outputs={"Out": [z]},
                    )
                    filled.append(z)
                else:
                    filled.append(g)
            grad_inputs[slot + GRAD] = filled

        grad_outputs: Dict[str, List[str]] = {}
        registered: List[Tuple[str, str]] = []
        for slot, names in op.inputs.items():
            outs = []
            produce = False
            for n in names:
                if n in needs:
                    gname = new_partial_name(n)
                    outs.append(gname)
                    registered.append((n, gname))
                    produce = True
                else:
                    # slot-aligned placeholder; value discarded
                    outs.append(f"{n}{GRAD}@UNUSED")
            if produce:
                grad_outputs[slot + GRAD] = outs
        if not grad_outputs:
            continue

        attrs = dict(op.attrs)
        attrs["__fwd_in_slots__"] = list(op.inputs.keys())
        block.append_op(
            type=op.type + "_grad",
            inputs=grad_inputs,
            outputs=grad_outputs,
            attrs=attrs,
            infer=False,  # grad shapes mirror forward inputs; skip re-trace
        )
        # set grad var metadata from forward vars
        for n, gname in registered:
            fv = block._find_var_recursive(n)
            gv = block._find_var_recursive(gname)
            if fv is not None and gv is not None:
                gv.shape = fv.shape
                gv.dtype = fv.dtype
        for n, gname in registered:
            partials[n].append(gname)

    # collect (target var, grad) — targets default to all trainable params
    if parameter_list is not None:
        target_names = list(parameter_list)
    else:
        target_names = [
            p.name
            for p in block.program.global_block().all_parameters()
            if p.trainable
        ]
    params_grads: List[Tuple[framework.Variable, framework.Variable]] = []
    for name in target_names:
        v = block._find_var_recursive(name)
        if v is None:
            continue
        g = finalize(name)
        if g is None:
            continue
        params_grads.append((v, block._find_var_recursive(g)))
    return params_grads


def calc_gradient(
    targets,
    inputs,
    target_gradients=None,
    no_grad_set=None,
):
    """Gradients of targets wrt inputs (reference backward.py:1665)."""
    if isinstance(targets, framework.Variable):
        targets = [targets]
    if isinstance(inputs, framework.Variable):
        inputs = [inputs]
    if len(targets) != 1:
        raise NotImplementedError("calc_gradient currently supports one target")
    loss = targets[0]
    names = [v.name for v in inputs]
    pg = append_backward(loss, parameter_list=names, no_grad_set=no_grad_set)
    by_name = {p.name: g for p, g in pg}
    block = loss.block
    outs = []
    for v in inputs:
        g = by_name.get(v.name)
        if g is None:
            gname = v.name + GRAD
            g = block._find_var_recursive(gname)
        outs.append(g)
    return outs


gradients = calc_gradient
