#!/usr/bin/env python
"""autotune — Pallas kernel config search over the persistent tuning
cache (ISSUE 13; paddle_tpu/tuning is the library, this is the CLI).

Subcommands:

  search   enumerate candidate configs per target, reject infeasible
           ones (VMEM footprint models + the HBM budget gate), measure
           survivors through the tools/op_bench.py single-op fence
           (FLAGS_benchmark timed loop; objective = the candidate op's
           OWN attributed device time from telemetry/cost.py under
           FLAGS_op_profile), and persist winners in the per-chip cache
           ($PADDLE_AUTOTUNE_CACHE, else
           ~/.cache/paddle_tpu/autotune/<chip>.json). Already-cached
           keys are skipped (100% cache hit on a re-run) unless
           --force.
  show     print the merged active cache (repo defaults <- user cache
           <- $PADDLE_AUTOTUNE_CACHE) or one explicit file.
  diff     compare two cache files entry by entry.

Examples:

    # CI smoke: tiny shapes, CPU-interpret kernels, deterministic
    PADDLE_AUTOTUNE_CACHE=/tmp/at.json python tools/autotune.py search --smoke

    # tune flash attention at the bench long-context shape (on a TPU)
    python tools/autotune.py search --flash 8:4096:4096:12:64 --dtype bfloat16

    # tune a ResNet stage conv (kxk stride-2 enables the s2d axis)
    python tools/autotune.py search --conv 8:56:56:64:128:3:3:2:2

    python tools/autotune.py show
    python tools/autotune.py diff old.json new.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS_DIR))  # repo root: paddle_tpu
if _TOOLS_DIR not in sys.path:  # tools/: op_bench (in-process import)
    sys.path.insert(0, _TOOLS_DIR)

EXIT_NO_FEASIBLE = 2


# ---------------------------------------------------------------------------
# target builders: (kernel key, candidate set, one-op measurement spec)
# ---------------------------------------------------------------------------


def _flash_targets(spec: str, dtype: str):
    """'b:sq:skv:nh:d[:dropout_prob]' -> one flash_bsh SearchTarget."""
    from paddle_tpu.tuning import configs, search

    parts = spec.split(":")
    b, sq, skv, nh, d = (int(x) for x in parts[:5])
    dropout = float(parts[5]) if len(parts) > 5 else 0.0
    h = nh * d
    cands, rejected = configs.flash_bsh_candidates(
        sq, skv, h, dtype, dropout=dropout > 0.0)
    attrs = {"num_heads": nh}
    if dropout > 0.0:
        attrs["dropout_prob"] = dropout

    def hbm_bytes(cfg):
        # the materialized dropout mask is the only axis that adds an
        # HBM-resident tensor: [B, nh, Sq, Skv] uint8, read by fwd+bwd
        return b * nh * sq * skv if cfg.get("mask") == "materialize" else 0

    return [search.SearchTarget(
        kernel="flash_bsh",
        key={"sq": sq, "skv": skv, "h": h, "dtype": dtype},
        candidates=cands, rejected=rejected,
        spec={"op_type": "fused_multihead_attention",
              "shapes": {"Q": (b, sq, h), "K": (b, skv, h),
                         "V": (b, skv, h)},
              "attrs": attrs, "out_slot": "Out", "dtype": dtype},
        hbm_bytes=hbm_bytes,
    )]


def _ln_targets(spec: str, dtype: str):
    """'r:h' -> one add_ln SearchTarget (layer_norm over the last axis
    routes through the fused kernel when the gate passes)."""
    from paddle_tpu.tuning import configs, search

    r, h = (int(x) for x in spec.split(":"))
    cands, rejected = configs.add_ln_candidates(r, h, dtype)
    return [search.SearchTarget(
        kernel="add_ln",
        key={"r": r, "h": h, "dtype": dtype},
        candidates=cands, rejected=rejected,
        spec={"op_type": "layer_norm",
              "shapes": {"X": (r, h), "Scale": (h,), "Bias": (h,)},
              "attrs": {"begin_norm_axis": 1, "epsilon": 1e-5},
              "out_slot": "Y", "dtype": dtype},
    )]


def _conv_targets(spec: str, dtype: str):
    """'n:h:w:c:o:kh:kw:sh:sw[:pad]' -> conv_bn row-block targets (+ the
    space-to-depth axis for kxk stride-2). pad: SAME (default) or
    VALID."""
    from paddle_tpu.ops.pallas import conv_bn as cb
    from paddle_tpu.tuning import configs, search

    parts = spec.split(":")
    n, h, w, c, o, kh, kw, sh, sw = (int(x) for x in parts[:9])
    pad = parts[9] if len(parts) > 9 else "SAME"
    strides = (sh, sw)
    pads = cb._resolve_pads(pad, h, w, kh, kw, strides)
    hp = h + pads[0][0] + pads[0][1]
    wp = w + pads[1][0] + pads[1][1]
    ho = (hp - kh) // sh + 1
    wo = (wp - kw) // sw + 1
    r = n * ho * wo
    op_spec = {
        "op_type": "fused_conv_bn",
        "shapes": {"Input": (n, h, w, c), "Filter": (o, c, kh, kw),
                   "Scale": (o,), "Bias": (o,), "Mean": (o,),
                   "Variance": (o,)},
        "attrs": {"data_format": "NHWC", "padding_algorithm": pad,
                  "strides": [sh, sw], "with_relu": 1},
        "out_slot": "Y", "dtype": dtype,
    }
    targets = []
    if (kh, kw) == (1, 1):
        cands, rej = configs.conv_bn_candidates("mm", r, c + o, dtype)
        targets.append(search.SearchTarget(
            kernel="conv_bn",
            key={"kind": "mm", "r": r, "w": c + o, "dtype": dtype},
            candidates=cands, rejected=rej, spec=op_spec))
    cands, rej = configs.conv_bn_candidates("apply", r, o, dtype)
    targets.append(search.SearchTarget(
        kernel="conv_bn",
        key={"kind": "apply", "r": r, "w": o, "dtype": dtype},
        candidates=cands, rejected=rej, spec=op_spec))
    s2d_cands, s2d_rej = configs.conv_bn_s2d_candidates(
        n, hp, wp, c, o, kh, kw, strides, dtype)
    if s2d_cands:
        targets.append(search.SearchTarget(
            kernel="conv_bn_s2d",
            key={"n": n, "h": h, "w": w, "c": c, "o": o, "kh": kh,
                 "kw": kw, "sh": sh, "sw": sw, "dtype": dtype},
            candidates=s2d_cands, rejected=s2d_rej, spec=op_spec))
    return targets


def _paged_targets(spec: str, dtype: str):
    """'b:maxseq:kvheads:headdim' -> one paged_attention SearchTarget.
    The serving kernel is not a registry op (it is called directly by
    the generation engine's decode step), so its spec carries a 'kind'
    marker and _make_measure times it through a direct jax loop instead
    of the op_bench fence. The key deliberately omits batch/seq: the
    winner is the KV POOL page size, a model-geometry property that
    kv_cache.from_budget looks up by (kv_heads, head_dim, dtype)."""
    from paddle_tpu.tuning import configs, search

    b, max_seq, kvh, d = (int(x) for x in spec.split(":"))
    cands, rejected = configs.paged_attention_candidates(
        kvh, d, dtype, max_seq)
    return [search.SearchTarget(
        kernel="paged_attention",
        key={"kv_heads": kvh, "head_dim": d, "dtype": dtype},
        candidates=cands, rejected=rejected,
        spec={"kind": "paged_attention", "b": b, "max_seq": max_seq,
              "kv_heads": kvh, "head_dim": d, "dtype": dtype},
    )]


def _smoke_targets():
    """Tiny CPU-interpret targets for the CI lane: every tunable kernel
    exercised end to end through the REAL lookup + measurement path in
    a couple of minutes."""
    return (
        _flash_targets("1:256:256:1:128", "float32")
        + _ln_targets("128:128", "float32")
        + _conv_targets("1:4:4:8:8:1:1:1:1", "float32")
        + _conv_targets("1:9:9:8:8:3:3:2:2", "float32")
        + _paged_targets("2:32:2:8", "float32")
    )


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _measure_paged_attention(spec: dict, config: dict, repeat: int) -> float:
    """Direct jax timing loop for the serving paged-attention kernel:
    build a KV pool layout at the candidate page size (pool page 0 is
    the trash page, so the table starts at id 1), run the kernel once
    to compile, then time `repeat` fenced iterations."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas import paged_attention as pa

    b, max_seq = int(spec["b"]), int(spec["max_seq"])
    kvh, d = int(spec["kv_heads"]), int(spec["head_dim"])
    page = int(config["page_size"])
    maxp = (max_seq + page - 1) // page
    rng = np.random.default_rng(0)
    dt = np.dtype(spec.get("dtype", "float32"))
    q = jnp.asarray(rng.standard_normal((b, kvh, d)), dtype=dt.name)
    kp = jnp.asarray(rng.standard_normal((b * maxp + 1, page, kvh, d)),
                     dtype=dt.name)
    vp = jnp.asarray(rng.standard_normal((b * maxp + 1, page, kvh, d)),
                     dtype=dt.name)
    table = jnp.asarray(
        np.arange(b * maxp, dtype=np.int32).reshape(b, maxp) + 1)
    lengths = jnp.full((b,), max_seq, dtype=jnp.int32)
    fn = jax.jit(pa.paged_attention)
    jax.block_until_ready(fn(q, kp, vp, table, lengths))  # compile
    t0 = _time.perf_counter()
    for _ in range(max(1, repeat)):
        out = fn(q, kp, vp, table, lengths)
    jax.block_until_ready(out)
    return (_time.perf_counter() - t0) / max(1, repeat) * 1e6


def _make_measure(objective: str, repeat: int, profile_steps: int):
    """The searcher's measure callable: pin the candidate through
    tuning.override (the production lookup path — the compile-cache key
    carries the override fingerprint, so every candidate compiles
    fresh), run the one-op program through op_bench's fence, return the
    objective in microseconds."""
    from paddle_tpu import tuning
    from paddle_tpu.tuning.search import mock_measure

    if objective == "mock":
        return mock_measure

    import op_bench

    def measure(target, config):
        if target.spec.get("kind") == "paged_attention":
            # not a registry op: no op_bench program exists for it
            return _measure_paged_attention(target.spec, config, repeat)
        with tuning.override(
                {target.kernel: {target.canonical: {"config": config}}}):
            row = op_bench.run_case(
                repeat=repeat,
                op_profile=objective == "device",
                op_profile_steps=profile_steps,
                **target.spec)
        if objective == "device" and row.get("op_device_us"):
            return float(row["op_device_us"])
        # no attributable device events (backend limitations): fall
        # back to the fenced wall latency so search still ranks
        return float(row["latency_us"])

    measure.source = f"op_bench:{objective}"
    return measure


def cmd_search(args) -> int:
    import paddle_tpu.fluid as fluid
    from paddle_tpu import tuning
    from paddle_tpu.tuning.cache import TuningCache, chip_kind
    from paddle_tpu.tuning.feasible import NoFeasibleConfig
    from paddle_tpu.tuning.search import Searcher

    targets = []
    for spec in args.flash or []:
        targets += _flash_targets(spec, args.dtype)
    for spec in args.ln or []:
        targets += _ln_targets(spec, args.dtype)
    for spec in args.conv or []:
        targets += _conv_targets(spec, args.dtype)
    for spec in args.paged or []:
        targets += _paged_targets(spec, args.dtype)
    if args.smoke:
        targets += _smoke_targets()
    if not targets:
        print("autotune search: no targets (use --flash/--ln/--conv/"
              "--paged or --smoke)", file=sys.stderr)
        return 1

    if args.force_pallas or args.smoke:
        # CPU/interpret smoke: pin the Pallas kernels so candidate
        # configs actually flow through the lookup sites
        from paddle_tpu.ops import attention

        attention.FORCE_PALLAS = True
    prev_flag = fluid.flags.get_flags(
        "FLAGS_kernel_autotune")["FLAGS_kernel_autotune"]
    fluid.flags.set_flags({"FLAGS_kernel_autotune": True})

    chip = chip_kind()
    path = args.cache or tuning.default_cache_path(chip)
    cache, _reason = TuningCache.load(path, expect_chip=chip)
    if cache is None:
        cache = TuningCache(chip, path=path)

    searcher = Searcher(
        cache, _make_measure(args.measure, args.repeat,
                             args.profile_steps),
        hbm_budget_bytes=args.hbm_budget)
    results = []
    infeasible = 0
    try:
        for t in targets:
            try:
                results.append(searcher.search(t, force=args.force))
            except NoFeasibleConfig as e:
                infeasible += 1
                print(f"# autotune: {e}", file=sys.stderr)
    finally:
        fluid.flags.set_flags({"FLAGS_kernel_autotune": prev_flag})
    saved = cache.save(path)
    hits = sum(1 for r in results if r.cache_hit)
    summary = {
        "cache": saved,
        "chip": chip,
        "fingerprint": cache.fingerprint(),
        "targets": len(targets),
        "searched": len(results) - hits,
        "cache_hits": hits,
        "infeasible": infeasible,
        "results": [r.to_json() for r in results],
    }
    print(json.dumps(summary if args.json else {
        k: v for k, v in summary.items() if k != "results"}))
    if infeasible and not results:
        return EXIT_NO_FEASIBLE
    return 0


# ---------------------------------------------------------------------------
# show / diff
# ---------------------------------------------------------------------------


def _load_for_show(path):
    from paddle_tpu.tuning.cache import TuningCache, load_active_cache

    if path:
        cache, reason = TuningCache.load(path)
        if cache is None:
            raise SystemExit(f"autotune: cannot load {path}: {reason}")
        return cache
    return load_active_cache(verbose=True)


def cmd_show(args) -> int:
    cache = _load_for_show(args.cache)
    if args.json:
        print(cache.to_blob(), end="")
        return 0
    print(f"autotune cache: chip={cache.chip} entries={len(cache)} "
          f"fingerprint={cache.fingerprint()}"
          + (f" path={cache.path}" if cache.path else " (merged view)"))
    for kernel in sorted(cache.entries):
        for key, entry in sorted(cache.entries[kernel].items()):
            us = entry.get("us")
            src = entry.get("source", "?")
            print(f"  {kernel:<12} {key:<44} -> {entry.get('config')}"
                  + (f"  [{us} us]" if us is not None else "")
                  + f"  ({src})")
    return 0


def cmd_diff(args) -> int:
    from paddle_tpu.tuning.cache import TuningCache

    out = {"added": [], "removed": [], "changed": [], "same": 0}
    sides = []
    for p in (args.a, args.b):
        cache, reason = TuningCache.load(p)
        if cache is None:
            raise SystemExit(f"autotune: cannot load {p}: {reason}")
        sides.append(cache)
    a, b = sides
    akeys = {(k, key) for k in a.entries for key in a.entries[k]}
    bkeys = {(k, key) for k in b.entries for key in b.entries[k]}
    for k, key in sorted(bkeys - akeys):
        out["added"].append({"kernel": k, "key": key,
                             "config": b.get(k, key).get("config")})
    for k, key in sorted(akeys - bkeys):
        out["removed"].append({"kernel": k, "key": key,
                               "config": a.get(k, key).get("config")})
    for k, key in sorted(akeys & bkeys):
        ea, eb = a.get(k, key), b.get(k, key)
        if ea.get("config") != eb.get("config"):
            out["changed"].append({
                "kernel": k, "key": key, "a": ea.get("config"),
                "b": eb.get("config"), "a_us": ea.get("us"),
                "b_us": eb.get("us")})
        else:
            out["same"] += 1
    if args.json:
        print(json.dumps(out))
    else:
        for verb in ("added", "removed", "changed"):
            for row in out[verb]:
                if verb == "changed":
                    print(f"~ {row['kernel']}[{row['key']}]: "
                          f"{row['a']} -> {row['b']}")
                else:
                    sign = "+" if verb == "added" else "-"
                    print(f"{sign} {row['kernel']}[{row['key']}]: "
                          f"{row['config']}")
        print(f"# {out['same']} identical, {len(out['added'])} added, "
              f"{len(out['removed'])} removed, "
              f"{len(out['changed'])} changed")
    return 1 if (out["added"] or out["removed"] or out["changed"]) else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="autotune", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("search", help="measure candidates, persist winners")
    sp.add_argument("--flash", action="append",
                    help="b:sq:skv:nh:d[:dropout] flash_bsh target")
    sp.add_argument("--ln", action="append", help="r:h add_ln target")
    sp.add_argument("--conv", action="append",
                    help="n:h:w:c:o:kh:kw:sh:sw[:pad] conv_bn target")
    sp.add_argument("--paged", action="append",
                    help="b:maxseq:kvheads:headdim paged_attention "
                    "page-size target (winner feeds "
                    "kv_cache.from_budget)")
    sp.add_argument("--smoke", action="store_true",
                    help="built-in tiny CPU-interpret targets (CI lane)")
    sp.add_argument("--dtype", default="float32")
    sp.add_argument("--cache", help="cache file to read+write "
                    "(default: $PADDLE_AUTOTUNE_CACHE or the user cache)")
    sp.add_argument("--measure", choices=("device", "latency", "mock"),
                    default="device",
                    help="objective: per-op device time (default), "
                    "fenced wall latency, or the deterministic mock")
    sp.add_argument("--repeat", type=int, default=10)
    sp.add_argument("--profile-steps", type=int, default=3)
    sp.add_argument("--force", action="store_true",
                    help="re-measure keys the cache already holds")
    sp.add_argument("--force-pallas", action="store_true",
                    help="pin the Pallas interpret kernels on CPU")
    sp.add_argument("--hbm-budget", type=int,
                    default=None, help="reject candidates whose extra "
                    "HBM residency exceeds this many bytes (default: "
                    "$PADDLE_HBM_BUDGET_BYTES; see also tools/memtop.py "
                    "--budget for whole-program gating)")
    sp.add_argument("--json", action="store_true",
                    help="full per-candidate results on stdout")
    sp.set_defaults(fn=cmd_search)

    sh = sub.add_parser("show", help="print a cache (or the merged view)")
    sh.add_argument("--cache", help="explicit cache file (default: the "
                    "merged active view)")
    sh.add_argument("--json", action="store_true")
    sh.set_defaults(fn=cmd_show)

    dp = sub.add_parser("diff", help="compare two cache files")
    dp.add_argument("a")
    dp.add_argument("b")
    dp.add_argument("--json", action="store_true")
    dp.set_defaults(fn=cmd_diff)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
