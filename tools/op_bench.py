"""Op micro-benchmark harness (reference operators/benchmark/op_tester.cc:
config-driven single-op timing).

Usage:
    python tools/op_bench.py matmul --shape X=1024x1024 --shape Y=1024x1024
    python tools/op_bench.py softmax --shape X=4096x4096 --repeat 50
    python tools/op_bench.py conv2d --shape Input=8x64x56x56 \
        --shape Filter=128x64x3x3 --attr strides=1,1 --out Output

    # the fused conv+BN(+relu) mega-kernel at a ResNet stage shape
    # (NHWC; Scale/Bias/Mean/Variance are the per-channel BN operands):
    python tools/op_bench.py fused_conv_bn \
        --shape Input=8x28x28x128 --shape Filter=128x128x3x3 \
        --shape Scale=128 --shape Bias=128 --shape Mean=128 \
        --shape Variance=128 \
        --attr data_format=NHWC --attr padding_algorithm=SAME \
        --attr with_relu=1 --out Y

Builds a one-op Program, runs it through the real Executor (whole-block
XLA), and reports steady-state latency after a compile warmup. --flag
sets FLAGS_* before the run (flag-gated kernels: FLAGS_conv_dw_im2col,
FLAGS_use_fused_ln, ...).
"""
import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def _parse_shape(s):
    name, dims = s.split("=")
    return name, tuple(int(d) for d in dims.lower().split("x"))


def _parse_attr(s):
    k, v = s.split("=", 1)
    try:
        vals = [float(x) if "." in x else int(x) for x in v.split(",")]
        return k, vals if len(vals) > 1 else vals[0]
    except ValueError:
        return k, v


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("op_type")
    ap.add_argument("--shape", action="append", default=[],
                    help="slot=AxBxC (float32 random input)")
    ap.add_argument("--attr", action="append", default=[])
    ap.add_argument("--out", default="Out", help="output slot name")
    ap.add_argument("--repeat", type=int, default=100)
    ap.add_argument("--flag", action="append", default=[],
                    help="FLAGS_name=value set before the run")
    args = ap.parse_args()

    import paddle_tpu.fluid as fluid

    if args.flag:
        fluid.flags.set_flags(dict(f.split("=", 1) for f in args.flag))

    shapes = dict(_parse_shape(s) for s in args.shape)
    attrs = dict(_parse_attr(a) for a in args.attr)

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        block = main_p.global_block()
        rng = np.random.RandomState(0)
        feed = {}
        ins = {}
        for slot, shape in shapes.items():
            n = f"in_{slot}"
            block.create_var(name=n, shape=shape, dtype=np.float32)
            feed[n] = rng.rand(*shape).astype(np.float32)
            ins[slot] = [n]
        block.create_var(name="out")
        block.append_op(type=args.op_type, inputs=ins,
                        outputs={args.out: ["out"]}, attrs=attrs)

    exe = fluid.Executor()
    exe.run(startup)
    import jax

    feed = {k: jax.device_put(v) for k, v in feed.items()}
    (o,) = exe.run(main_p, feed=feed, fetch_list=["out"])  # compile
    np.asarray(o)
    t0 = time.perf_counter()
    for _ in range(args.repeat):
        (o,) = exe.run(main_p, feed=feed, fetch_list=["out"],
                       return_numpy=False)
    np.asarray(o)
    dt = (time.perf_counter() - t0) / args.repeat
    print(json.dumps({
        "op": args.op_type,
        "shapes": {k: list(v) for k, v in shapes.items()},
        "attrs": {k: v for k, v in attrs.items()},
        "latency_us": round(dt * 1e6, 2),
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
