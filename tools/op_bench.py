"""Op micro-benchmark harness (reference operators/benchmark/op_tester.cc:
config-driven single-op timing).

Usage:
    python tools/op_bench.py matmul --shape X=1024x1024 --shape Y=1024x1024
    python tools/op_bench.py softmax --shape X=4096x4096 --repeat 50
    python tools/op_bench.py conv2d --shape Input=8x64x56x56 \
        --shape Filter=128x64x3x3 --attr strides=1,1 --out Output

    # the fused conv+BN(+relu) mega-kernel at a ResNet stage shape
    # (NHWC; Scale/Bias/Mean/Variance are the per-channel BN operands):
    python tools/op_bench.py fused_conv_bn \
        --shape Input=8x28x28x128 --shape Filter=128x128x3x3 \
        --shape Scale=128 --shape Bias=128 --shape Mean=128 \
        --shape Variance=128 \
        --attr data_format=NHWC --attr padding_algorithm=SAME \
        --attr with_relu=1 --out Y

    # sweep mode: comma-separated shape lists expand cartesian, one
    # JSON line per combination
    python tools/op_bench.py matmul --sweep \
        --shape X=512x512,1024x1024 --shape Y=512x512,1024x1024

Builds a one-op Program, runs it through the real Executor (whole-block
XLA), and reports steady-state latency after a compile warmup. The
timed loop runs under FLAGS_benchmark (the sync fence — every
dispatch blocks until the device finishes, so per-iteration latency is
honest); --no-fence restores the async-dispatch loop. --op-profile
additionally traces a few steps under FLAGS_op_profile and reports the
op's OWN attributed device time (telemetry/cost.py) — the objective
the kernel autotuner ranks candidates by. --flag sets FLAGS_* before
the run (flag-gated kernels: FLAGS_conv_dw_im2col, FLAGS_use_fused_ln,
FLAGS_kernel_autotune, ...).

This module is also the LIBRARY the autotuner and CI share
(tools/autotune.py imports run_case) so there is exactly one
measurement path.
"""
import argparse
import itertools
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def _parse_shape(s):
    name, dims = s.split("=")
    return name, tuple(int(d) for d in dims.lower().split("x"))


def _parse_shape_list(s):
    """'slot=AxB,CxD' -> (slot, [(A,B), (C,D)]) — the --sweep form."""
    name, dims = s.split("=")
    return name, [tuple(int(d) for d in v.lower().split("x"))
                  for v in dims.split(",") if v]


def _parse_attr(s):
    k, v = s.split("=", 1)
    try:
        vals = [float(x) if "." in x else int(x) for x in v.split(",")]
        return k, vals if len(vals) > 1 else vals[0]
    except ValueError:
        return k, v


def build_one_op_program(op_type, shapes, attrs, out_slot="Out",
                         dtype="float32"):
    """One-op Program + random feed (dtype, default float32) for every
    input slot. Returns (main_program, startup_program, feed dict)."""
    import paddle_tpu.fluid as fluid

    np_dtype = np.dtype(dtype) if dtype != "bfloat16" else None
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        block = main_p.global_block()
        rng = np.random.RandomState(0)
        feed = {}
        ins = {}
        for slot, shape in shapes.items():
            n = f"in_{slot}"
            arr = rng.rand(*shape).astype(np.float32)
            if np_dtype is not None:
                arr = arr.astype(np_dtype)
            else:
                import jax.numpy as jnp

                arr = jnp.asarray(arr, jnp.bfloat16)
            block.create_var(name=n, shape=shape, dtype=arr.dtype)
            feed[n] = arr
            ins[slot] = [n]
        block.create_var(name="out")
        block.append_op(type=op_type, inputs=ins,
                        outputs={out_slot: ["out"]}, attrs=attrs)
    return main_p, startup, feed


def run_case(op_type, shapes, attrs, out_slot="Out", repeat=100, warmup=1,
             fence=True, op_profile=False, op_profile_steps=3,
             dtype="float32"):
    """Measure one (op, shapes, attrs) case; returns the machine row.

    fence=True wraps the timed loop in FLAGS_benchmark so each run()
    blocks until the device finishes. op_profile=True re-runs a few
    steps under FLAGS_op_profile and adds `op_device_us` — the op's own
    attributed per-step device time from telemetry/cost.py, the
    autotuner's ranking objective (0.0 when the backend produced no
    attributable device events; callers fall back to latency_us)."""
    import jax

    import paddle_tpu.fluid as fluid

    main_p, startup, feed = build_one_op_program(
        op_type, shapes, attrs, out_slot, dtype=dtype)
    exe = fluid.Executor()
    exe.run(startup)
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    for _ in range(max(1, warmup)):
        (o,) = exe.run(main_p, feed=feed, fetch_list=["out"])  # compile
    np.asarray(o)

    prev = fluid.flags.get_flags("FLAGS_benchmark")["FLAGS_benchmark"]
    if fence:
        fluid.flags.set_flags({"FLAGS_benchmark": True})
    try:
        t0 = time.perf_counter()
        for _ in range(repeat):
            (o,) = exe.run(main_p, feed=feed, fetch_list=["out"],
                           return_numpy=False)
        np.asarray(o)
        dt = (time.perf_counter() - t0) / max(1, repeat)
    finally:
        fluid.flags.set_flags({"FLAGS_benchmark": prev})

    row = {
        "op": op_type,
        "shapes": {k: list(v) for k, v in shapes.items()},
        "attrs": {k: v for k, v in attrs.items()},
        "latency_us": round(dt * 1e6, 2),
        "fenced": bool(fence),
        "repeat": repeat,
        "dtype": str(dtype),
        "backend": jax.default_backend(),
    }
    if op_profile:
        from paddle_tpu.telemetry import cost

        rep = cost.profile_executor_run(
            exe, main_p, feed, ["out"], steps=op_profile_steps, warmup=1)
        row["op_device_us"] = round(
            rep.device_ms_for(op_type=op_type) * 1e3, 3)
        row["op_profile_coverage"] = round(rep.coverage, 4)
    return row


def sweep_cases(shape_lists):
    """Cartesian product over per-slot shape lists (slot order as
    given): yields {slot: shape} dicts."""
    names = [n for n, _ in shape_lists]
    for combo in itertools.product(*[v for _, v in shape_lists]):
        yield dict(zip(names, combo))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("op_type")
    ap.add_argument("--shape", action="append", default=[],
                    help="slot=AxBxC (float32 random input); with "
                    "--sweep, slot=AxB,CxD lists expand cartesian")
    ap.add_argument("--attr", action="append", default=[])
    ap.add_argument("--out", default="Out", help="output slot name")
    ap.add_argument("--dtype", default="float32",
                    help="input dtype (float32/bfloat16/...)")
    ap.add_argument("--repeat", type=int, default=100)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--sweep", action="store_true",
                    help="cartesian product over comma-separated --shape "
                    "lists; one JSON line per combination")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable mode: JSON rows only on "
                    "stdout (diagnostics to stderr)")
    ap.add_argument("--no-fence", action="store_true",
                    help="async-dispatch timed loop (no FLAGS_benchmark "
                    "sync fence)")
    ap.add_argument("--op-profile", action="store_true",
                    help="also report the op's own attributed device "
                    "time per step (FLAGS_op_profile + "
                    "telemetry/cost.py) — the autotuner objective")
    ap.add_argument("--flag", action="append", default=[],
                    help="FLAGS_name=value set before the run")
    args = ap.parse_args()

    import paddle_tpu.fluid as fluid

    if args.flag:
        fluid.flags.set_flags(dict(f.split("=", 1) for f in args.flag))

    attrs = dict(_parse_attr(a) for a in args.attr)
    if args.sweep:
        shape_lists = [_parse_shape_list(s) for s in args.shape]
        cases = list(sweep_cases(shape_lists))
    else:
        cases = [dict(_parse_shape(s) for s in args.shape)]

    ok = 0
    for i, shapes in enumerate(cases):
        if args.sweep and not args.json:
            print(f"# case {i + 1}/{len(cases)}: "
                  + " ".join(f"{k}={list(v)}" for k, v in shapes.items()),
                  file=sys.stderr)
        try:
            row = run_case(
                args.op_type, shapes, attrs, out_slot=args.out,
                repeat=args.repeat, warmup=args.warmup,
                fence=not args.no_fence, op_profile=args.op_profile,
                dtype=args.dtype)
            ok += 1
        except Exception as e:  # noqa: BLE001 — a cartesian sweep may
            # produce shape combos the op rejects; report and move on
            row = {
                "op": args.op_type,
                "shapes": {k: list(v) for k, v in shapes.items()},
                "attrs": attrs, "error": str(e),
            }
        print(json.dumps(row))
    return 0 if (ok or not cases) else 1


if __name__ == "__main__":
    sys.exit(main())
