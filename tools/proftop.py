#!/usr/bin/env python
"""proftop — per-op device-time attribution for Program IR graphs
(telemetry/cost.py; the `top` for one XLA-compiled training step).

Two modes:

  --model <name>   build the bench model (proglint's model-builder
                   plumbing), train a few profiled steps on the local
                   backend under FLAGS_op_profile, and print the joined
                   cost report: top-K ops by device time, per-op-type /
                   per-layer rollups, attribution coverage, and the
                   measured-MFU gauge cross-checked against bench.py's
                   model-formula flops.
  --trace_dir D    aggregate an EXISTING xplane trace (any jax profiler
                   dump) by HLO instruction; pass --hlo <file> (the
                   optimized HLO text, e.g. Executor.aot_step(...)
                   .as_text()) to additionally join op scopes.

Examples:

    python tools/proftop.py --model resnet50
    python tools/proftop.py --model bert --steps 5 --topk 10 --json
    python tools/proftop.py --trace_dir /tmp/prof --hlo step.hlo.txt
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS_DIR))  # repo root: paddle_tpu
if _TOOLS_DIR not in sys.path:  # tools/: proglint (in-process importers)
    sys.path.insert(0, _TOOLS_DIR)

from proglint import MODELS, build_bench_model  # noqa: E402 — path above


def _random_feed(model, cfg, args):
    import numpy as np

    rng = np.random.RandomState(0)
    if model.startswith("resnet"):
        return {
            "image": rng.rand(args.batch, 3, args.image_size,
                              args.image_size).astype(np.float32),
            "label": rng.randint(0, cfg.num_classes,
                                 (args.batch, 1)).astype(np.int64),
        }
    from paddle_tpu.models.bert import random_pretrain_batch

    return random_pretrain_batch(cfg, args.batch, args.seq, args.max_preds,
                                 seed=0)


def _formula_flops(model, cfg, args):
    """bench.py's closed-form model flops per step (fwd+bwd) — the
    cross-check input for the measured-MFU gauge."""
    if model.startswith("resnet"):
        from paddle_tpu.models.resnet import resnet_step_flops

        return resnet_step_flops(cfg, args.batch, args.image_size)
    import bench

    return bench._bert_step_flops(cfg, args.batch, args.seq)


def _profile_model(args):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.telemetry import cost

    main, startup, feeds, loss, cfg = build_bench_model(
        args.model, args.batch, args.image_size, args.seq, args.max_preds)
    with fluid.program_guard(main, startup):
        if args.model.startswith("resnet"):
            opt = fluid.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9)
        else:
            opt = fluid.optimizer.AdamOptimizer(learning_rate=1e-4)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    feed = _random_feed(args.model, cfg, args)
    return cost.profile_executor_run(
        exe, main, feed, [loss], steps=args.steps,
        formula_flops_per_step=_formula_flops(args.model, cfg, args),
        model=args.model)


def _aggregate_trace(args):
    from paddle_tpu.fluid import profiler
    from paddle_tpu.telemetry import cost

    events = profiler.xplane_op_events(args.trace_dir)
    hlo_text = ""
    if args.hlo:
        with open(args.hlo) as f:
            hlo_text = f.read()
    return cost.build_cost_report(events, hlo_text, steps=args.steps,
                                  model=None), events


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="proftop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--model", help=f"bench model to build and profile: "
                     f"{', '.join(MODELS)}")
    src.add_argument("--trace_dir", help="existing xplane trace dir to "
                     "aggregate (jax profiler dump)")
    ap.add_argument("--hlo", help="optimized HLO text file to join op "
                    "scopes from (with --trace_dir)")
    ap.add_argument("--steps", type=int, default=3,
                    help="profiled steps (--model) / steps the trace "
                    "covers (--trace_dir; scales per-step numbers)")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--max-preds", type=int, default=8)
    ap.add_argument("--topk", type=int, default=20)
    ap.add_argument("--json", action="store_true",
                    help="one JSON object (the full report) on stdout")
    args = ap.parse_args(argv)

    if args.model:
        report = _profile_model(args)
    else:
        report, events = _aggregate_trace(args)
        if not args.hlo:
            # no HLO join: the honest output is the raw instruction table
            rows = sorted(((n, e["dur_ps"] / 1e9, e["count"])
                           for n, e in events.items()),
                          key=lambda r: -r[1])
            if args.json:
                print(json.dumps({"instructions": [
                    {"name": n, "device_ms": round(ms, 3), "count": c}
                    for n, ms, c in rows[:args.topk]]}))
            else:
                print(f"{'instruction':<50}{'ms':>10}{'count':>8}")
                for n, ms, c in rows[:args.topk]:
                    print(f"{n[:49]:<50}{ms:>10.3f}{c:>8}")
            return 0 if rows else 1

    if args.json:
        print(json.dumps(report.to_json(args.topk)))
    else:
        print(report.format_table(args.topk))
    if not report.rows:
        print("proftop: no attributed op events (is the trace empty, or "
              "was the step traced without FLAGS_op_profile?)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
