#!/usr/bin/env python
"""ABI-drift check for the Go inference client (clients/go/paddle).

The CI image ships no Go toolchain, so `go vet/build` only runs on
machines that have one (tools/ci.sh). This check closes the "silently
unverified" gap (VERDICT r4 weak #5) with what CAN be verified here:

1. every symbol the Go client dlsym()s exists in the extern "C" block
   of paddle_tpu/native/capi.cc;
2. the cgo preamble's function-pointer typedefs carry the same arity as
   the C definitions they are cast to (the class of silent-corruption
   bug dlopen clients are prone to);
3. the .go file is structurally sound (balanced braces/parens outside
   strings and comments).

Exit 0 = in sync. Any drift fails CI loudly.
"""
from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
GO = REPO / "clients" / "go" / "paddle" / "paddle.go"
CAPI = REPO / "paddle_tpu" / "native" / "capi.cc"

# cgo shim typedef -> the C symbol its pointer is cast to (paddle.go
# NewPredictor wiring)
TYPEDEF_TO_SYMBOL = {
    "pd_create_fn": "PD_PredictorCreate",
    "pd_destroy_fn": "PD_PredictorDestroy",
    "pd_set_in_fn": "PD_SetInputFloat",
    "pd_run_fn": "PD_PredictorRun",
    "pd_get_out_fn": "PD_GetOutputFloat",
}


def _strip_comments_strings(src: str, line_comment: str) -> str:
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.S)
    src = re.sub(rf"{line_comment}[^\n]*", " ", src)
    src = re.sub(r'"(?:\\.|[^"\\])*"', '""', src)
    src = re.sub(r"'(?:\\.|[^'\\])*'", "''", src)
    return src


def _arity(args: str) -> int:
    args = args.strip()
    if not args or args == "void":
        return 0
    depth = 0
    n = 1
    for ch in args:
        if ch in "(<[":
            depth += 1
        elif ch in ")>]":
            depth -= 1
        elif ch == "," and depth == 0:
            n += 1
    return n


def main() -> int:
    go_src = GO.read_text()
    c_src = CAPI.read_text()
    errors = []

    # 1. dlsym'd symbols exist in capi.cc
    dlsymed = set(re.findall(r'sym\(lib,\s*"(PD_[A-Za-z_]+)"\)', go_src))
    if not dlsymed:
        errors.append("no dlsym'd PD_* symbols found in paddle.go "
                      "(parser drift?)")
    exported = set(re.findall(
        r"^[A-Za-z_][A-Za-z_ *]*?\b(PD_[A-Za-z_]+)\s*\(", c_src, re.M))
    for s in sorted(dlsymed - exported):
        errors.append(f"paddle.go dlsym()s {s} but capi.cc does not "
                      f"define it")

    # 2. typedef arity matches the C definition arity
    c_clean = _strip_comments_strings(c_src, "//")
    for td, sym_name in TYPEDEF_TO_SYMBOL.items():
        m = re.search(
            rf"typedef\s+[^(]*\(\s*\*\s*{td}\s*\)\s*\(([^;]*)\)\s*;",
            go_src)
        if not m:
            errors.append(f"paddle.go preamble missing typedef {td}")
            continue
        go_arity = _arity(m.group(1))
        cm = re.search(
            rf"\b{sym_name}\s*\(([^{{;]*)\)\s*\{{", c_clean)
        if not cm:
            errors.append(f"capi.cc: cannot locate definition of "
                          f"{sym_name}")
            continue
        c_arity = _arity(cm.group(1))
        if go_arity != c_arity:
            errors.append(
                f"arity drift: {td} declares {go_arity} args but "
                f"{sym_name} takes {c_arity}")

    # 3. structural balance of the Go source
    clean = _strip_comments_strings(go_src, "//")
    for o, c in (("{", "}"), ("(", ")"), ("[", "]")):
        if clean.count(o) != clean.count(c):
            errors.append(
                f"paddle.go unbalanced {o!r}{c!r}: "
                f"{clean.count(o)} vs {clean.count(c)}")

    if errors:
        print("go client ABI check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"go client ABI check OK: {len(dlsymed)} dlsym symbols "
          f"present, {len(TYPEDEF_TO_SYMBOL)} signatures in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
