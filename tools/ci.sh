#!/usr/bin/env bash
# CI entry (reference: paddle/scripts/paddle_build.sh): run the whole
# verification ladder on the virtual-device CPU backend.
#
#   tools/ci.sh          # tests + dryrun + compile check
#   tools/ci.sh quick    # tests only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== unit + integration tests (8-device virtual CPU mesh) =="
# tee the run into TESTLOG (committed artifact): pytest tail + the
# DOTS_PASSED count the tier-1 gate greps for — so every CI run leaves
# an auditable record of what actually passed. Slow chaos drills are
# excluded here (tier-1 wall time stays flat) and run explicitly below.
rm -f /tmp/ci_pytest.log
python -m pytest tests/ -x -q -m 'not slow' 2>&1 | tee /tmp/ci_pytest.log
{
  echo "# TESTLOG — written by tools/ci.sh; pytest tail + dot count"
  echo "# (regenerate: tools/ci.sh quick)"
  tail -n 25 /tmp/ci_pytest.log
  echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/ci_pytest.log | tr -cd . | wc -c)"
} > TESTLOG

echo "== PS chaos smoke (deterministic fault injection) =="
# tiny 2-trainer + 1-pserver jobs under PADDLE_PS_FAULT_SPEC: injected
# connection drops must train to the EXACT no-fault loss (retry+dedup),
# and a mid-run pserver kill must recover via supervised respawn +
# snapshot preload (tests/test_ps_faults.py, the @slow process drills)
python -m pytest tests/test_ps_faults.py -q -m slow

echo "== PS replication drills (R=2 failover + hedging) =="
# ISSUE 7 acceptance: kill ONE pserver of a replicated pair mid-run —
# trainers fail over to the backups with NO respawn-wait and the loss
# trace is BIT-identical to the no-fault run; and an injected per-verb
# latency tail on one replica is absorbed by backup hedges (hedges won
# > 0, gather p95 back under the injected tail). The R=1 default paths
# are covered byte-for-byte by the tier-1 unit tests above
# (tests/test_ps_replication.py, tests/test_ps_faults.py)
python -m pytest tests/test_ps_replication.py -q -m slow

echo "== elastic resize drill (kill-one-of-four -> dp=3 bit-parity) =="
# ISSUE 8 acceptance: a dp=4 job loses one trainer PERMANENTLY; the
# coordinator-backed launcher evicts it after its per-rank budget,
# bumps the membership epoch and restarts the survivors at dp=3 from
# the last checkpoint — and the post-resize loss trace must be
# BIT-identical to a clean dp=3 run resumed from the same checkpoint
# step. The fast coordinator/lease/flagz/world-size unit tests run in
# tier-1 above (tests/test_elastic.py)
python -m pytest tests/test_elastic.py -q -m slow

echo "== parallel heavy parity (slow lane: ring/pipeline/SP + breadth) =="
# heavy parametrizations / breadth sweeps run here so tier-1's
# 'not slow' pass stays inside its wall-clock budget. NOT included:
# test_dist_train's two-process gloo drills and test_moe's ep4 parity
# drill, which are currently red in this container (ROADMAP records
# both) — run them explicitly when working on those paths
python -m pytest tests/test_ring_attention.py tests/test_pipeline.py \
  tests/test_sequence_models.py tests/test_bert.py \
  tests/test_hapi_text.py -q -m slow

echo "== preemption drill (SIGTERM mid-training -> resume, exact trace) =="
# a launcher job is SIGTERM'd mid-training: the trainer commits a final
# checkpoint and exits 75, the elastic restart auto-resumes, and the
# concatenated loss trace must be EXACTLY the uninterrupted run's; the
# launcher-level grace handler is drilled the same way
# (tests/test_checkpoint.py, the @slow process drills)
python -m pytest tests/test_checkpoint.py -q -m slow

echo "== async/sharded checkpoint drill (kill rank 1 pre-global-commit) =="
# ISSUE 10 acceptance: a 2-rank sharded-checkpoint job loses rank 1
# between its shard commit and the global commit — the step must stay
# TORN (invisible to restore, which serves the previous global step),
# `ckpt_doctor --gc` must remove the torn dir, and the relaunched job
# must resume to a loss trace bit-identical to an uninterrupted run's.
# The fast async/coalesce/fault-matrix/doctor units run in tier-1 above
# (tests/test_checkpoint_async.py)
python -m pytest tests/test_checkpoint_async.py -q -m slow

echo "== telemetry smoke (3-step CPU train, JSONL schema + monotone steps) =="
# ISSUE 4 acceptance: a metrics-armed run must emit one kind="step"
# record per executor step with the breakdown keys, monotone in step;
# FLAGS_benchmark fences the device so device_ms is honest
rm -f /tmp/ci_metrics.jsonl
PADDLE_METRICS_PATH=/tmp/ci_metrics.jsonl FLAGS_benchmark=1 \
  JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", [16, 8], append_batch_size=False)
    y = layers.data("y", [16, 1], append_batch_size=False)
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
exe = fluid.Executor()
exe.run(startup)
rng = np.random.RandomState(0)
xa = rng.rand(16, 8).astype(np.float32)
ya = xa.sum(1, keepdims=True).astype(np.float32)
for _ in range(3):
    exe.run(main, feed={"x": xa, "y": ya}, fetch_list=[loss])
PY
python - <<'PY'
import json

recs = [json.loads(l) for l in open("/tmp/ci_metrics.jsonl")]
steps = [r for r in recs if r["kind"] == "step"]
assert len(steps) >= 4, f"expected startup+3 step records, got {len(steps)}"
need = {"step", "data_wait_ms", "compile_ms", "device_ms", "cache_hit",
        "ckpt_save_ms", "peak_hbm_bytes", "retraces", "ts", "rank"}
for r in steps:
    missing = need - set(r)
    assert not missing, f"step record missing {missing}: {r}"
idx = [r["step"] for r in steps]
assert idx == sorted(idx) and len(set(idx)) == len(idx), f"steps not monotone: {idx}"
assert all(r["fenced"] for r in steps), "FLAGS_benchmark run must fence"
assert any(r["cache_hit"] for r in steps[2:]), "steady state should hit the cache"
print(f"telemetry smoke OK: {len(steps)} step records, monotone, schema complete")
PY

echo "== step-trace drill (causal spans -> critical-path attribution) =="
# ISSUE 9 acceptance: a 2-trainer sync job with a deterministic 400ms
# stall injected on ONE trainer's push_gradients — the merged trace's
# per-round critical path must attribute >= 400ms to the correct
# (rank, verb) hop, the whole-job timeline must gain pserver +
# coordinator lanes, and PADDLE_TRACING unset must leave wire bytes and
# the loss trace bit-identical (tests/test_tracing.py; the fast
# propagation/parentage/exemplar/tracetop units run in tier-1 above)
python -m pytest tests/test_tracing.py -q -m slow

echo "== proglint (static program verification over bench models) =="
# ISSUE 5 acceptance: the bench-model programs — forward, +backward,
# +conv_bn_fusion — must carry ZERO error-severity findings (dangling
# refs, dtype clashes, stale last-writer links, torn grad graphs, ...).
# The same checks run flag-gated in the Executor (FLAGS_program_verify);
# this is the standalone CI entry. Exit is nonzero on any error finding.
# --pair additionally builds the for_test eval clone and verifies the
# whole-job train/eval contract (startup pairing, is_test flips, no
# grad/optimizer leakage, BN moving stats aliased).
JAX_PLATFORMS=cpu python tools/proglint.py --model resnet50
JAX_PLATFORMS=cpu python tools/proglint.py --model resnet50 --fuse --backward --pair
JAX_PLATFORMS=cpu python tools/proglint.py --model bert --backward --pair

echo "== proglint over saved artifacts (frozen decode program + saved model dir) =="
# ISSUE 20 acceptance: the SHIPPED artifacts lint clean too — the
# frozen serving decode program (state-carrying KV write-back pattern)
# and a save_inference_model dir, both through the --program loader
rm -rf /tmp/ci_proglint_frozen /tmp/ci_proglint_saved
JAX_PLATFORMS=cpu python - <<'PY'
import json

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import io as fio
from paddle_tpu.fluid import layers
from paddle_tpu.inference.freeze import freeze_program

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data(name="x", shape=[1, 4], dtype="float32")
    blk = main.global_block()
    cache = blk.create_var(name="decode_cache", shape=[1, 4],
                           dtype="float32", persistable=True)
    sblk = startup.global_block()
    sc = sblk.create_var(name="decode_cache", shape=[1, 4],
                         dtype="float32", persistable=True)
    sblk.append_op(type="fill_constant", inputs={}, outputs={"Out": [sc]},
                   attrs={"shape": [1, 4], "dtype": "float32", "value": 0.0})
    t = layers.elementwise_add(cache, x)   # read decode state
    layers.assign(t, output=cache)         # write new state back
    out = layers.scale(t, scale=2.0)

exe = fluid.Executor()
scope = fluid.executor.Scope()
with fluid.scope_guard(scope):
    exe.run(startup)
    # freeze_program itself runs verify_program + the scope-aware lint
    # of the captured weights unconditionally; this lane re-lints the
    # SAVED artifact through the same CLI a serving operator would use
    fm = freeze_program(main, scope=scope, feed_names=["x"],
                        fetch_list=[out])
    assert fm.meta["state_vars"] == ["decode_cache"]
    import os

    os.makedirs("/tmp/ci_proglint_frozen", exist_ok=True)
    fio._atomic_write_bytes("/tmp/ci_proglint_frozen/__model__",
                            fio._serialize_program(fm.program))
    fio._atomic_write_bytes(
        "/tmp/ci_proglint_frozen/__meta__.json",
        json.dumps({"feed_names": fm.feed_names,
                    "fetch_names": fm.fetch_names}).encode())
    fio.save_inference_model("/tmp/ci_proglint_saved", ["x"], [out], exe,
                             main_program=main)
print("frozen decode program + save_inference_model dir written")
PY
JAX_PLATFORMS=cpu python tools/proglint.py --program /tmp/ci_proglint_frozen
JAX_PLATFORMS=cpu python tools/proglint.py --program /tmp/ci_proglint_saved

echo "== proglint --fix round-trip (saved train pickle repair, bit-identical) =="
# ISSUE 20 acceptance: a deliberately-torn saved training program must
# (1) fail the lint, (2) repair via --fix --in-place, (3) re-lint clean
# with NO flags, and (4) — the breakage being entirely off the live
# graph — train to a loss trace BIT-identical to the pristine save
rm -rf /tmp/ci_proglint_fix
JAX_PLATFORMS=cpu python - <<'PY'
import json
import pickle

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import io as fio
from paddle_tpu.fluid import layers

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", [16, 8], append_batch_size=False)
    y = layers.data("y", [16, 1], append_batch_size=False)
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
exe = fluid.Executor()
exe.run(startup)
fio.save_train_model(exe, "/tmp/ci_proglint_fix", ["x", "y"], loss,
                     main_program=main, startup_program=startup)


def losses(dirname):
    e = fluid.Executor()
    sc = fluid.executor.Scope()
    with fluid.scope_guard(sc):
        m, s, feeds, loss_name = fio.load_train_model(e, dirname)
        rng = np.random.RandomState(0)
        xa = rng.rand(16, 8).astype(np.float32)
        ya = xa.sum(1, keepdims=True).astype(np.float32)
        out = []
        for _ in range(3):
            (lv,) = e.run(m, feed={"x": xa, "y": ya},
                          fetch_list=[loss_name])
            out.append(float(np.asarray(lv).ravel()[0]))
    return out


base = losses("/tmp/ci_proglint_fix")
json.dump(base, open("/tmp/ci_proglint_fix/baseline.json", "w"))

# tear the saved program: a consumer of a @GRAD no op produces (the
# orphaned-grad-chain shape a forward rewrite leaves behind) — an
# ERROR-severity finding, but entirely off the live graph, so the
# mechanical repair must preserve training semantics exactly
with open("/tmp/ci_proglint_fix/__train_model__", "rb") as f:
    meta = pickle.load(f)
m = fio._deserialize_program(meta["main"])
blk = m.global_block()
blk.create_var(name="phantom@GRAD", shape=(16, 1), dtype="float32")
blk.append_op(type="scale", inputs={"X": ["phantom@GRAD"]},
              outputs={"Out": ["ci_debris_0"]}, attrs={"scale": 1.0})
meta["main"] = fio._serialize_program(m)
fio._atomic_write_bytes("/tmp/ci_proglint_fix/__train_model__",
                        pickle.dumps(meta))
print("pristine baseline recorded; saved program torn")
PY
if JAX_PLATFORMS=cpu python tools/proglint.py --program /tmp/ci_proglint_fix; then
  echo "proglint: the torn train pickle must exit nonzero"; exit 1
fi
JAX_PLATFORMS=cpu python tools/proglint.py --program /tmp/ci_proglint_fix \
  --fix --in-place
JAX_PLATFORMS=cpu python tools/proglint.py --program /tmp/ci_proglint_fix
JAX_PLATFORMS=cpu python - <<'PY'
import json

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import io as fio

e = fluid.Executor()
sc = fluid.executor.Scope()
with fluid.scope_guard(sc):
    m, s, feeds, loss_name = fio.load_train_model(e, "/tmp/ci_proglint_fix")
    rng = np.random.RandomState(0)
    xa = rng.rand(16, 8).astype(np.float32)
    ya = xa.sum(1, keepdims=True).astype(np.float32)
    fixed = []
    for _ in range(3):
        (lv,) = e.run(m, feed={"x": xa, "y": ya}, fetch_list=[loss_name])
        fixed.append(float(np.asarray(lv).ravel()[0]))
base = json.load(open("/tmp/ci_proglint_fix/baseline.json"))
assert fixed == base, f"fix round-trip not bit-identical: {fixed} vs {base}"
print(f"fix round-trip OK: repaired program re-lints clean, "
      f"3-step loss trace bit-identical {fixed}")
PY

echo "== proftop smoke (per-op device-time attribution + debugz) =="
# slow-lane proftop/memtop CLI drills (wall-time triage: the resnet18
# CLI tests are the heaviest in their suites and their acceptance bars
# re-run below on resnet50 + bert anyway)
python -m pytest tests/test_proftop.py -q -m slow
# ISSUE 6 acceptance: a 3-step profiled CPU train (FLAGS_op_profile
# named scopes -> xplane join) must attribute >=90% of device-op time
# to named op scopes on BOTH bench models, every reported row must
# carry an op index + user callstack, and the measured-MFU gauge must
# agree with bench.py's model formula within the documented 2x
# tolerance (same time base; the ratio compares flop accounting)
JAX_PLATFORMS=cpu python tools/proftop.py --model resnet50 --steps 3 \
  --json > /tmp/ci_proftop_resnet50.json
JAX_PLATFORMS=cpu python tools/proftop.py --model bert --steps 3 \
  --json > /tmp/ci_proftop_bert.json
python - <<'PY'
import json

for model in ("resnet50", "bert"):
    rep = json.load(open(f"/tmp/ci_proftop_{model}.json"))
    assert rep["model"] == model
    assert rep["coverage"] >= 0.9, (model, rep["coverage"])
    assert rep["rows"], f"{model}: no attributed op rows"
    for row in rep["rows"]:
        assert row["op_index"] >= 0, (model, row)
        assert row["layer"], (model, row["scope"], "missing callstack")
    ratio = rep["measured_mfu"] / rep["formula_mfu"]
    assert 0.5 <= ratio <= 2.0, (model, ratio)
    print(f"proftop {model}: coverage {rep['coverage']:.3f}, "
          f"{len(rep['rows'])} rows, measured/formula MFU {ratio:.2f}")
PY
# debugz: the introspection server must serve one valid /metrics scrape
# (and /steps) off a 3-step train armed only by PADDLE_DEBUGZ_PORT
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import urllib.request

os.environ["PADDLE_DEBUGZ_PORT"] = "0"  # ephemeral port
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", [16, 8], append_batch_size=False)
    y = layers.data("y", [16, 1], append_batch_size=False)
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
exe = fluid.Executor()
exe.run(startup)
rng = np.random.RandomState(0)
xa = rng.rand(16, 8).astype(np.float32)
ya = xa.sum(1, keepdims=True).astype(np.float32)
for _ in range(3):
    exe.run(main, feed={"x": xa, "y": ya}, fetch_list=[loss])
from paddle_tpu.telemetry import debugz

assert debugz.armed(), "PADDLE_DEBUGZ_PORT did not arm the server"
port = debugz._server.server_address[1]
scrape = urllib.request.urlopen(
    f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
assert "# TYPE executor_steps_total counter" in scrape
steps = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/steps", timeout=5).read().decode())
assert steps and steps[-1]["step"] >= steps[0]["step"]
print(f"debugz OK: /metrics scraped ({len(scrape.splitlines())} lines), "
      f"{len(steps)} step records on /steps")
PY

echo "== memtop smoke (per-op HBM attribution + budget gate) =="
# OOM-doctor subprocess drill (slow lane): a 1KB PADDLE_HBM_BUDGET_BYTES
# must make the compile-time gate refuse the step and leave a memrec
# flight-record naming the culprit buffer's owning op and user layer
python -m pytest tests/test_memtop.py -q -m slow
# ISSUE 11 acceptance: the measured join must attribute >=90% of XLA's
# reported peak bytes to IR ops / named state with user callstacks, and
# the static estimate must agree with the measured peak within the
# documented tolerance; the --budget gate must round-trip (generous
# budget -> rc 0, absurd budget -> rc 2 naming the overflow)
JAX_PLATFORMS=cpu python tools/memtop.py --model resnet50 \
  --image-size 32 --json > /tmp/ci_memtop_resnet50.json
python - <<'PY'
import json

rep = json.load(open("/tmp/ci_memtop_resnet50.json"))
assert rep["model"] == "resnet50"
assert rep["coverage"] >= 0.9, rep["coverage"]
assert rep["measured_peak_bytes"] > 0 and rep["static_peak_bytes"] > 0
assert 0.3 <= rep["static_over_measured"] <= 3.0, rep["static_over_measured"]
assert rep["buffers"], "no sized buffers"
for row in rep["buffers"]:
    assert row["bytes"] > 0 and row["layer"], (row["name"], "no callstack")
cats = rep["categories"]
assert cats["params"] > 0 and cats["gradients"] > 0
print(f"memtop resnet50: coverage {rep['coverage']:.3f}, "
      f"static/measured {rep['static_over_measured']:.2f}x, "
      f"{len(rep['buffers'])} buffers")
PY
JAX_PLATFORMS=cpu python tools/memtop.py --model bert --static-only \
  --budget 64000000000 --json > /dev/null \
  || { echo "memtop: generous budget must pass"; exit 1; }
if JAX_PLATFORMS=cpu python tools/memtop.py --model bert --static-only \
  --budget 1000 --json > /tmp/ci_memtop_budget.json; then
  echo "memtop: 1KB budget must exit nonzero"; exit 1
fi
python - <<'PY'
import json

rep = json.load(open("/tmp/ci_memtop_budget.json"))
assert rep["over_budget"] is True and rep["budget_bytes"] == 1000
print("memtop budget gate OK (rc 2, over_budget flagged)")
PY
# FLAGS_mem_profile end-to-end: a 3-step profiled resnet50 train must
# publish the hbm_* gauges and one kind="mem_report" JSONL record per
# compiled program, leaving the step-record schema untouched
rm -f /tmp/ci_memprof.jsonl
PADDLE_METRICS_PATH=/tmp/ci_memprof.jsonl FLAGS_mem_profile=1 \
  JAX_PLATFORMS=cpu python - <<'PY'
import sys

sys.path.insert(0, "tools")
import numpy as np
from proglint import build_bench_model

import paddle_tpu.fluid as fluid

main, startup, feeds, loss, cfg = build_bench_model(
    "resnet50", 2, 32)
with fluid.program_guard(main, startup):
    fluid.optimizer.MomentumOptimizer(
        learning_rate=0.1, momentum=0.9).minimize(loss)
exe = fluid.Executor()
exe.run(startup)
rng = np.random.RandomState(0)
feed = {"image": rng.rand(2, 3, 32, 32).astype(np.float32),
        "label": rng.randint(0, cfg.num_classes, (2, 1)).astype(np.int64)}
for _ in range(3):
    exe.run(main, feed=feed, fetch_list=[loss])
from paddle_tpu.telemetry import get_registry

assert get_registry().gauge("hbm_static_peak_bytes").value > 0
assert get_registry().gauge("hbm_model_bytes").value > 0
PY
python - <<'PY'
import json

recs = [json.loads(l) for l in open("/tmp/ci_memprof.jsonl")]
mems = [r for r in recs if r["kind"] == "mem_report"]
steps = [r for r in recs if r["kind"] == "step"]
assert mems, "FLAGS_mem_profile produced no mem_report record"
assert mems[-1]["static_peak_bytes"] > 0
assert mems[-1]["categories"]["params"] > 0
assert steps and all("peak_hbm_bytes" in r for r in steps)
print(f"mem_profile smoke OK: {len(mems)} mem_report record(s), "
      f"step schema intact over {len(steps)} steps")
PY

echo "== numerics lane (tensor stats + NaN doctor + SDC bitflip drill) =="
# ISSUE 12 acceptance drills, slow lane: the 2-process bitflip drill
# (one corrupted dp rank must be NAMED by the divergence event within
# K steps, all ranks flight-dump, the rank is evicted) runs here; the
# fast doctor/AMP/clip/fingerprint units run in tier-1 above
python -m pytest tests/test_numerics.py -q -m slow
# 3-step stats-armed train: kind="numerics" records present with the
# per-layer stat keys AND the kind="step" schema intact
rm -f /tmp/ci_numerics.jsonl
PADDLE_METRICS_PATH=/tmp/ci_numerics.jsonl FLAGS_tensor_stats=1 \
  JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", [16, 8], append_batch_size=False)
    y = layers.data("y", [16, 1], append_batch_size=False)
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
exe = fluid.Executor()
exe.run(startup)
rng = np.random.RandomState(0)
xa = rng.rand(16, 8).astype(np.float32)
ya = xa.sum(1, keepdims=True).astype(np.float32)
for _ in range(3):
    exe.run(main, feed={"x": xa, "y": ya}, fetch_list=[loss])
PY
python - <<'PY'
import json

recs = [json.loads(l) for l in open("/tmp/ci_numerics.jsonl")]
stats = [r for r in recs if r["kind"] == "numerics"
         and r.get("event") == "stats"]
steps = [r for r in recs if r["kind"] == "step"]
assert len(stats) == 3, f"expected 3 sampled stat records, got {len(stats)}"
grads = {k: v for k, v in stats[-1]["watch"].items()
         if v["kind"] == "grad"}
assert grads, "no per-layer gradient watches"
for label, row in grads.items():
    assert {"nan", "inf", "max_abs", "l2"} <= set(row), (label, row)
    assert row["nan"] == 0 and row["inf"] == 0
need = {"step", "data_wait_ms", "compile_ms", "device_ms", "cache_hit",
        "ckpt_save_ms", "peak_hbm_bytes", "retraces", "ts", "rank"}
for r in steps:
    assert need <= set(r), f"step record missing {need - set(r)}"
print(f"numerics smoke OK: {len(stats)} stat records over "
      f"{len(grads)} gradient watches, step schema intact")
PY
# numtop smoke: the CLI must render the series the train just wrote
JAX_PLATFORMS=cpu python tools/numtop.py --metrics /tmp/ci_numerics.jsonl \
  --json > /tmp/ci_numtop.json
python - <<'PY'
import json

rep = json.load(open("/tmp/ci_numtop.json"))
grads = {k: v for k, v in rep["watches"].items() if v["kind"] == "grad"}
assert grads and all(w["samples"] == 3 for w in grads.values()), rep
print(f"numtop smoke OK: {len(rep['watches'])} watched series")
PY

echo "== goodput lane (ledger + fleet view + kill-one-of-two drill) =="
# ISSUE 15 acceptance drills, slow lane: a 2-rank --fleetz_port job
# loses one trainer mid-run — goodtop must classify EVERY wall-clock
# second (unclassified residual < 2%), decompose the restart incident
# into detection/respawn/recompile/replay, and the mid-job /fleetz
# scrape must serve both ranks from ONE endpoint; the fast
# classification/stitch/TCP-aggregation/reader-stage units run in
# tier-1 above (tests/test_goodput.py)
python -m pytest tests/test_goodput.py -q -m slow
# 3-step goodput-armed train: ledger rows wall-exact, goodput records
# in the sink, step schema (incl. the new idle_ms) intact, and
# goodtop --json renders the job view
rm -rf /tmp/ci_goodput; mkdir -p /tmp/ci_goodput
rm -f /tmp/ci_goodput.jsonl
PADDLE_METRICS_PATH=/tmp/ci_goodput.jsonl PADDLE_GOODPUT=1 \
  PADDLE_GOODPUT_DIR=/tmp/ci_goodput PADDLE_GOODPUT_EVERY=1 \
  JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", [16, 8], append_batch_size=False)
    y = layers.data("y", [16, 1], append_batch_size=False)
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
exe = fluid.Executor()
exe.run(startup)
rng = np.random.RandomState(0)
xa = rng.rand(16, 8).astype(np.float32)
ya = xa.sum(1, keepdims=True).astype(np.float32)
for _ in range(3):
    exe.run(main, feed={"x": xa, "y": ya}, fetch_list=[loss])
PY
python - <<'PY'
import glob
import json

recs = [json.loads(l) for l in open("/tmp/ci_goodput.jsonl")]
steps = [r for r in recs if r["kind"] == "step"]
assert len(steps) >= 4, f"expected startup+3 step records, got {len(steps)}"
need = {"step", "data_wait_ms", "compile_ms", "device_ms", "cache_hit",
        "idle_ms", "ckpt_save_ms", "peak_hbm_bytes", "retraces", "ts",
        "rank"}
for r in steps:
    assert need <= set(r), f"step record missing {need - set(r)}"
gsum = [r for r in recs if r["kind"] == "goodput"]
assert gsum, "no kind=goodput summary records in the sink"
assert gsum[-1]["buckets_ms"]["productive_step"] > 0
(ledger,) = glob.glob("/tmp/ci_goodput/goodput.*.jsonl")
rows = [json.loads(l) for l in open(ledger)]
assert rows[0]["event"] == "birth"
for r in rows:
    if "buckets" in r:  # every wall second classified, wall-exact
        assert abs(sum(r["buckets"].values())
                   - (r["t1"] - r["t0"]) * 1e3) < 0.5, r
print(f"goodput smoke OK: {len(steps)} step records (idle_ms present), "
      f"{len(gsum)} ledger summaries, wall-exact intervals in {ledger}")
PY
JAX_PLATFORMS=cpu python tools/goodtop.py /tmp/ci_goodput --json \
  > /tmp/ci_goodtop.json
python - <<'PY'
import json

view = json.load(open("/tmp/ci_goodtop.json"))
assert view["ranks"], "goodtop found no ledgers"
assert view["job"]["goodput_ratio"] is not None
assert view["job"]["unclassified_frac"] < 0.02, view["job"]
print(f"goodtop smoke OK: job goodput "
      f"{100 * view['job']['goodput_ratio']:.1f}%, residual "
      f"{100 * view['job']['unclassified_frac']:.2f}%")
PY

echo "== autotune lane (CPU-interpret smoke search + cache reuse) =="
# ISSUE 13 acceptance: a tiny-shape search over all three tunable
# kernels (flash_bsh / add_ln / conv_bn incl. the s2d axis) must run
# the REAL measurement path (op_bench fence + per-op device-time
# objective, interpret-mode Pallas kernels) and produce a cache file;
# the second run must be a 100% cache hit that leaves the file
# byte-identical. Heavier shape sweeps stay manual (autotune.md).
rm -f /tmp/ci_autotune.json
PADDLE_AUTOTUNE_CACHE=/tmp/ci_autotune.json JAX_PLATFORMS=cpu \
  python tools/autotune.py search --smoke --repeat 2 --profile-steps 2 \
  2>/dev/null | tee /tmp/ci_autotune_run1.json
cp /tmp/ci_autotune.json /tmp/ci_autotune.first
PADDLE_AUTOTUNE_CACHE=/tmp/ci_autotune.json JAX_PLATFORMS=cpu \
  python tools/autotune.py search --smoke --repeat 2 --profile-steps 2 \
  2>/dev/null | tee /tmp/ci_autotune_run2.json
cmp /tmp/ci_autotune.first /tmp/ci_autotune.json
python - <<'PY'
import json

r1 = json.load(open("/tmp/ci_autotune_run1.json"))
r2 = json.load(open("/tmp/ci_autotune_run2.json"))
assert r1["searched"] == r1["targets"] > 0 and r1["infeasible"] == 0, r1
assert r2["cache_hits"] == r2["targets"] and r2["searched"] == 0, r2
assert r1["fingerprint"] == r2["fingerprint"]
cache = json.load(open("/tmp/ci_autotune.json"))
for kernel in ("flash_bsh", "add_ln", "conv_bn", "conv_bn_s2d",
               "paged_attention"):
    assert cache["entries"].get(kernel), f"no {kernel} entries"
print(f"autotune lane OK: {r1['targets']} targets searched, second run "
      f"100% cache hit, file byte-identical (chip={r1['chip']})")
PY
# show/diff must render the cache the search just wrote
JAX_PLATFORMS=cpu python tools/autotune.py show \
  --cache /tmp/ci_autotune.json | head -3
JAX_PLATFORMS=cpu python tools/autotune.py diff \
  /tmp/ci_autotune.first /tmp/ci_autotune.json

echo "== serving lane (admission/failover/drain/hedge drills) =="
# ISSUE 14 acceptance, slow lane: (1) overload burst — at 2x
# sustainable offered load the server sheds with EXPLICIT Overloaded
# replies, every accepted request meets its deadline, and served/shed
# counters reconcile exactly with the client's view; (2) the
# kill-one-of-two launch.py --serve drill — SIGKILL one replica
# mid-stream, the client fails over with zero accepted requests lost,
# the supervisor respawns it and the recovered replica rejoins serving
# after re-adopting the current (live-synced) weights; (3) injected
# `slow:infer` tail on one replica — the client hedge races the other
# and wins; (4) SIGTERM graceful drain — stop admitting, finish
# in-flight, exit 0. Fast freeze/scheduler/fence units run in tier-1.
python -m pytest tests/test_serving.py -q -m slow

echo "== autoregressive overload drill (paged KV vs padded recompute) =="
# ISSUE 16 acceptance: the SAME autoregressive burst (shared 64-token
# system prompt + unique tails, iteration-level continuous batching)
# against the paged-KV engine and the r19-style padded recompute
# baseline — the paged path must do strictly less model work (position
# counters: O(n) vs O(n^2)), serve strictly MORE tokens/s, and shed
# STRICTLY no more requests. Fast parity/pool/prefix/eviction units
# run in tier-1 above (tests/test_kv_serving.py)
python -m pytest tests/test_kv_serving.py -q -m slow

echo "== crash-tolerant generation drills (mid-decode kill + KV preemption) =="
# ISSUE 17 acceptance: (1) chaos drill — two generation replicas, one
# armed with stall:gen_decode_step + crash:gen_decode_step (os._exit
# mid-decode with multiple streams in flight): ZERO lost generations,
# the books reconcile exactly (accepted == finished, no sheds), and
# every resumed output is bit-identical to the no-fault baseline;
# (2) KV-pressure drill — pool exhaustion preempts the victim with the
# most remaining work and resumes it (never deadline-expires it),
# preempt_positions == resume_positions exactly, and
# PADDLE_SERVE_RESUME=0 restores the r21 FIFO token streams byte for
# byte. Fast resume/dedup/failover/sampling units run in tier-1 above
# (tests/test_gen_resume.py)
python -m pytest tests/test_gen_resume.py -q -m slow

echo "== serving-trace lane (traced burst + stall attribution) =="
# ISSUE 19 acceptance: a traced 16-request burst with one injected
# stall:gen_decode_step tail — >=90% of every completed request's
# engine wall time is attributed to spans (queue_wait / prefill /
# pro-rata decode_step / peer_prefill), the stalled step's co-batched
# victims cite it through the serve_tpot_ms exemplar trace_id, the
# flightrec dumps reconstruct end-to-end through tools/reqtop.py, and
# a no-tracing rerun is token-bit-identical. Fast span-parentage /
# SLO-histogram / flag-off-bit-identity / servez / reqtop units run in
# tier-1 above (tests/test_serving_trace.py)
python -m pytest tests/test_serving_trace.py -q -m slow

echo "== control-plane lane (coordinator kill-and-respawn + standby promotion) =="
# ISSUE 18 acceptance: (1) kill-and-respawn drill — the durable job
# coordinator (PADDLE_COORD_SNAPSHOT_SECS armed) is killed at its 25th
# handled verb while 2 trainers + 1 pserver train with sharded
# checkpoints in flight; the launcher respawns it from its snapshot+WAL
# on the same port, trainers ride the outage out in grace mode — ZERO
# evictions, zero elastic restarts, the checkpoint stream reaches its
# final global commit, and the loss trace is bit-identical to the
# no-fault run; (2) standby-promotion drill — the primary dies for
# good, the warm standby promotes itself behind the +2 incarnation
# fence, clients fail over down the ordered endpoint list, and the
# promoted coordinator still exercises PS election authority (the
# promote RPC lands on the caught-up backup). Fast snapshot/WAL/fence/
# grace units run in tier-1 above (tests/test_coordinator_ha.py)
python -m pytest tests/test_coordinator_ha.py -q -m slow

echo "== bench smoke (CPU, tiny shapes, 2 steps) =="
BENCH_MODEL="${BENCH_SMOKE_MODEL:-resnet18}" python bench.py --smoke \
  | tee /tmp/ci_smoke.json
python - <<'PY'
import json

recs = [json.loads(l) for l in open("/tmp/ci_smoke.json")
        if l.strip().startswith("{")]
assert len(recs) == 1, f"bench --smoke must emit exactly one JSON line, got {len(recs)}"
r = recs[0]
assert r.get("value", 0) > 0 and "metric" in r and "mfu" in r, r
print("bench smoke JSON OK:", r["metric"], r["value"], r["unit"])
PY

if [[ "${1:-}" == "quick" ]]; then
  exit 0
fi

echo "== multichip dryrun (dp*tp, dp*pp, dp*sp ring attention, dp*ep MoE) =="
python __graft_entry__.py 8

echo "== single-chip forward compile check =="
python - <<'PY'
import __graft_entry__ as g

fn, args = g.entry()
out = fn(*args)
print("entry() compiled and ran:", [getattr(v, "shape", None) for v in out])
PY

echo "== FFI clients =="
# the Go client's ABI is checked against capi.cc on EVERY run (dlsym
# symbol presence + signature arity, tools/check_go_client.py); full
# compilation additionally runs wherever a Go toolchain exists
python tools/check_go_client.py
if command -v go >/dev/null 2>&1; then
  (cd clients/go/paddle && go vet . && go build .)
  echo "go client: built"
else
  echo "go client: ABI-checked only (no Go toolchain for compile; "
  echo "  clients/go/README.md documents the consumer-side build)"
fi

echo "== sdist build =="
python setup.py --quiet sdist
echo "CI OK"
