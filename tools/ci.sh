#!/usr/bin/env bash
# CI entry (reference: paddle/scripts/paddle_build.sh): run the whole
# verification ladder on the virtual-device CPU backend.
#
#   tools/ci.sh          # tests + dryrun + compile check
#   tools/ci.sh quick    # tests only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== unit + integration tests (8-device virtual CPU mesh) =="
# tee the run into TESTLOG (committed artifact): pytest tail + the
# DOTS_PASSED count the tier-1 gate greps for — so every CI run leaves
# an auditable record of what actually passed. Slow chaos drills are
# excluded here (tier-1 wall time stays flat) and run explicitly below.
rm -f /tmp/ci_pytest.log
python -m pytest tests/ -x -q -m 'not slow' 2>&1 | tee /tmp/ci_pytest.log
{
  echo "# TESTLOG — written by tools/ci.sh; pytest tail + dot count"
  echo "# (regenerate: tools/ci.sh quick)"
  tail -n 25 /tmp/ci_pytest.log
  echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/ci_pytest.log | tr -cd . | wc -c)"
} > TESTLOG

echo "== PS chaos smoke (deterministic fault injection) =="
# tiny 2-trainer + 1-pserver jobs under PADDLE_PS_FAULT_SPEC: injected
# connection drops must train to the EXACT no-fault loss (retry+dedup),
# and a mid-run pserver kill must recover via supervised respawn +
# snapshot preload (tests/test_ps_faults.py, the @slow process drills)
python -m pytest tests/test_ps_faults.py -q -m slow

echo "== preemption drill (SIGTERM mid-training -> resume, exact trace) =="
# a launcher job is SIGTERM'd mid-training: the trainer commits a final
# checkpoint and exits 75, the elastic restart auto-resumes, and the
# concatenated loss trace must be EXACTLY the uninterrupted run's; the
# launcher-level grace handler is drilled the same way
# (tests/test_checkpoint.py, the @slow process drills)
python -m pytest tests/test_checkpoint.py -q -m slow

echo "== bench smoke (CPU, tiny shapes, 2 steps) =="
BENCH_MODEL="${BENCH_SMOKE_MODEL:-resnet18}" python bench.py --smoke \
  | tee /tmp/ci_smoke.json
python - <<'PY'
import json

recs = [json.loads(l) for l in open("/tmp/ci_smoke.json")
        if l.strip().startswith("{")]
assert len(recs) == 1, f"bench --smoke must emit exactly one JSON line, got {len(recs)}"
r = recs[0]
assert r.get("value", 0) > 0 and "metric" in r and "mfu" in r, r
print("bench smoke JSON OK:", r["metric"], r["value"], r["unit"])
PY

if [[ "${1:-}" == "quick" ]]; then
  exit 0
fi

echo "== multichip dryrun (dp*tp, dp*pp, dp*sp ring attention, dp*ep MoE) =="
python __graft_entry__.py 8

echo "== single-chip forward compile check =="
python - <<'PY'
import __graft_entry__ as g

fn, args = g.entry()
out = fn(*args)
print("entry() compiled and ran:", [getattr(v, "shape", None) for v in out])
PY

echo "== FFI clients =="
# the Go client's ABI is checked against capi.cc on EVERY run (dlsym
# symbol presence + signature arity, tools/check_go_client.py); full
# compilation additionally runs wherever a Go toolchain exists
python tools/check_go_client.py
if command -v go >/dev/null 2>&1; then
  (cd clients/go/paddle && go vet . && go build .)
  echo "go client: built"
else
  echo "go client: ABI-checked only (no Go toolchain for compile; "
  echo "  clients/go/README.md documents the consumer-side build)"
fi

echo "== sdist build =="
python setup.py --quiet sdist
echo "CI OK"
