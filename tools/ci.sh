#!/usr/bin/env bash
# CI entry (reference: paddle/scripts/paddle_build.sh): run the whole
# verification ladder on the virtual-device CPU backend.
#
#   tools/ci.sh          # tests + dryrun + compile check
#   tools/ci.sh quick    # tests only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== unit + integration tests (8-device virtual CPU mesh) =="
python -m pytest tests/ -x -q

if [[ "${1:-}" == "quick" ]]; then
  exit 0
fi

echo "== multichip dryrun (dp*tp, dp*pp, dp*sp ring attention, dp*ep MoE) =="
python __graft_entry__.py 8

echo "== single-chip forward compile check =="
python - <<'PY'
import __graft_entry__ as g

fn, args = g.entry()
out = fn(*args)
print("entry() compiled and ran:", [getattr(v, "shape", None) for v in out])
PY

echo "== FFI clients =="
# the Go client's ABI is checked against capi.cc on EVERY run (dlsym
# symbol presence + signature arity, tools/check_go_client.py); full
# compilation additionally runs wherever a Go toolchain exists
python tools/check_go_client.py
if command -v go >/dev/null 2>&1; then
  (cd clients/go/paddle && go vet . && go build .)
  echo "go client: built"
else
  echo "go client: ABI-checked only (no Go toolchain for compile; "
  echo "  clients/go/README.md documents the consumer-side build)"
fi

echo "== sdist build =="
python setup.py --quiet sdist
echo "CI OK"
