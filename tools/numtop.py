#!/usr/bin/env python
"""numtop — training-numerics series + NaN-doctor report viewer
(telemetry/numerics.py; the numerics-side sibling of proftop/memtop).

Two input modes:

  --metrics <file.jsonl>   parse a PADDLE_METRICS_PATH sink file and
                           render the kind="numerics" records: the
                           per-watch stat series (per-layer gradient
                           l2 / max-abs / nan+inf counts over the
                           sampled steps), AMP loss-scale transitions,
                           and any SDC divergence verdicts
  --doctor <numrec.json>   pretty-print a NaN-provenance flight-record
                           (the numrec.<tag>.json the bad-step guard
                           dumps): first non-finite producer, user
                           layer, operand stats, grad-norm history

`--series` additionally prints the raw per-step rows for every watch
(default: one summary row per watch); `--json` emits one JSON object.

Examples:

    python tools/numtop.py --metrics /tmp/metrics.jsonl
    python tools/numtop.py --metrics /tmp/metrics.jsonl --series --watch fc_0
    python tools/numtop.py --doctor /tmp/traces/numrec.trainer0.json
    python tools/numtop.py --metrics /tmp/metrics.jsonl --json
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def load_numerics_records(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from a killed writer
            if rec.get("kind") == "numerics":
                out.append(rec)
    return out


def build_series(records: List[dict]) -> dict:
    """{watch_label: {"kind", "steps": [...], "rows": [stat dict ...]}}
    from the event="stats" records, plus amp + divergence lists."""
    series: Dict[str, dict] = {}
    amp = []
    divergences = []
    doctors = []
    for rec in records:
        ev = rec.get("event")
        if ev == "stats":
            for label, row in (rec.get("watch") or {}).items():
                ent = series.setdefault(
                    label, {"kind": row.get("kind"), "steps": [],
                            "rows": []})
                ent["steps"].append(rec.get("step"))
                ent["rows"].append(row)
        elif ev == "amp_scale":
            amp.append(rec)
        elif ev == "divergence":
            divergences.append(rec)
        elif ev == "doctor":
            doctors.append(rec)
    return {"series": series, "amp": amp, "divergences": divergences,
            "doctors": doctors}


def summarize_watch(ent: dict) -> dict:
    rows = ent["rows"]
    if ent.get("kind") == "clip_gnorm":
        vals = [r.get("value", 0.0) for r in rows]
        return {"kind": "clip_gnorm", "samples": len(rows),
                "last": vals[-1] if vals else 0.0,
                "max": max(vals) if vals else 0.0,
                "clipped": sum(1 for r in rows if r.get("clipped"))}
    return {
        "kind": ent.get("kind"), "samples": len(rows),
        "last_l2": rows[-1].get("l2", 0.0) if rows else 0.0,
        "max_l2": max((r.get("l2", 0.0) for r in rows), default=0.0),
        "max_abs": max((r.get("max_abs", 0.0) for r in rows),
                       default=0.0),
        "nan_steps": sum(1 for r in rows if r.get("nan")),
        "inf_steps": sum(1 for r in rows if r.get("inf")),
    }


def format_metrics(data: dict, series: bool, watch: str,
                   topk: int) -> str:
    lines = []
    items = [(label, ent) for label, ent in data["series"].items()
             if watch in label]
    # grads first (the series people page in for), then by max l2
    items.sort(key=lambda kv: (kv[1].get("kind") != "grad",
                               -summarize_watch(kv[1]).get(
                                   "max_l2", summarize_watch(kv[1]).get(
                                       "max", 0.0))))
    lines.append(f"numtop: {len(items)} watched series"
                 + (f" matching {watch!r}" if watch else ""))
    lines.append(f"{'watch':<38}{'kind':>11}{'n':>5}{'last l2':>12}"
                 f"{'max l2':>12}{'max|x|':>12}{'nan':>5}{'inf':>5}")
    for label, ent in items[:topk]:
        s = summarize_watch(ent)
        if s["kind"] == "clip_gnorm":
            lines.append(f"{label[:37]:<38}{s['kind']:>11}"
                         f"{s['samples']:>5}{s['last']:>12.4g}"
                         f"{s['max']:>12.4g}{'-':>12}"
                         f"{'-':>5}{s['clipped']:>5}")
            continue
        lines.append(f"{label[:37]:<38}{s['kind']:>11}{s['samples']:>5}"
                     f"{s['last_l2']:>12.4g}{s['max_l2']:>12.4g}"
                     f"{s['max_abs']:>12.4g}{s['nan_steps']:>5}"
                     f"{s['inf_steps']:>5}")
    if series:
        for label, ent in items[:topk]:
            lines.append(f"-- {label} --")
            for step, row in zip(ent["steps"], ent["rows"]):
                lines.append(f"  step {step}: {json.dumps(row)}")
    if data["amp"]:
        lines.append("-- AMP loss-scale events --")
        for rec in data["amp"]:
            lines.append(f"  step {rec.get('step')}: "
                         f"{rec.get('change')} "
                         f"{rec.get('old')} -> {rec.get('new')}")
    if data["divergences"]:
        lines.append("-- SDC divergence verdicts --")
        for rec in data["divergences"]:
            lines.append(
                f"  step {rec.get('detected_step')}: odd-rank-out "
                f"{rec.get('odd_rank_out')} "
                f"(method {rec.get('method')})")
    if data["doctors"]:
        lines.append("-- NaN-doctor runs --")
        for rec in data["doctors"]:
            where = (f"op#{rec['op_index']} [{rec.get('op_type')}] -> "
                     f"{rec.get('output_var')!r}"
                     if rec.get("op_index") is not None else "(no op "
                     "attributed)")
            lines.append(f"  {rec.get('reason')}: {where}")
    return "\n".join(lines)


def format_doctor(rec: dict) -> str:
    lines = [f"numrec: {rec.get('reason', '?')}"]
    if rec.get("provenance") == "op":
        lines.append(f"first non-finite producer: op#{rec['op_index']} "
                     f"[{rec['op_type']}] -> {rec['output_var']!r} "
                     f"(slot {rec.get('output_slot')})")
        uf = rec.get("user_frame")
        if uf:
            lines.append(f"user layer: {uf[0]}:{uf[1]} in {uf[2]}")
        st = rec.get("output_stats") or {}
        lines.append(f"output: nan={st.get('nan')} inf={st.get('inf')} "
                     f"max|x|={st.get('max_abs')} l2={st.get('l2')}")
        lines.append("operands:")
        for op in rec.get("operands") or []:
            s = op.get("stats") or {}
            lines.append(
                f"  {op.get('slot')}:{op.get('var')} "
                f"nan={s.get('nan')} inf={s.get('inf')} "
                f"max|x|={s.get('max_abs')} l2={s.get('l2')}")
    elif rec.get("provenance") == "input":
        s = rec.get("stats") or {}
        lines.append(f"poisoned INPUT {rec.get('var')!r}: "
                     f"nan={s.get('nan')} inf={s.get('inf')} — the "
                     f"step did not produce the non-finite values, the "
                     f"feed/state carried them in")
    else:
        lines.append(f"bisection: "
                     f"{rec.get('bisect_skipped') or rec.get('bisect_error') or '?'}")
    hist = rec.get("grad_history") or []
    if hist:
        lines.append(f"grad-norm history leading in "
                     f"({len(hist)} samples):")
        for h in hist[-8:]:
            grads = {label: round(row.get('l2', 0.0), 6)
                     for label, row in (h.get("watch") or {}).items()
                     if row.get("kind") == "grad"}
            lines.append(f"  step {h.get('step')}: {json.dumps(grads)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="numtop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--metrics",
                    help="PADDLE_METRICS_PATH JSONL file to render")
    ap.add_argument("--doctor",
                    help="numrec.<tag>.json NaN flight-record to render")
    ap.add_argument("--watch", default="",
                    help="substring filter over watch labels")
    ap.add_argument("--series", action="store_true",
                    help="print the raw per-step rows too")
    ap.add_argument("--topk", type=int, default=30)
    ap.add_argument("--json", action="store_true",
                    help="one JSON object on stdout")
    args = ap.parse_args(argv)
    if bool(args.metrics) == bool(args.doctor):
        ap.error("exactly one of --metrics / --doctor is required")

    if args.doctor:
        rec = json.load(open(args.doctor))
        if args.json:
            print(json.dumps(rec))
        else:
            print(format_doctor(rec))
        return 0

    records = load_numerics_records(args.metrics)
    data = build_series(records)
    if args.json:
        out = {
            "watches": {label: dict(summarize_watch(ent),
                                    steps=ent["steps"],
                                    rows=ent["rows"])
                        for label, ent in data["series"].items()
                        if args.watch in label},
            "amp": data["amp"],
            "divergences": data["divergences"],
            "doctors": data["doctors"],
        }
        print(json.dumps(out))
    else:
        print(format_metrics(data, args.series, args.watch, args.topk))
    if not records:
        print("numtop: no kind=\"numerics\" records found (run with "
              "FLAGS_tensor_stats=1 and PADDLE_METRICS_PATH set)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
