#!/usr/bin/env python
"""proglint — static lint for Program IR graphs (fluid/analysis).

Lints a bench-model program built in-process, or any saved program
(`__model__` pickle written by fluid.io.save_inference_model /
save_train_model), and exits nonzero on error-severity findings. The
same checks run flag-gated inside the Executor (FLAGS_program_verify)
and around the rewrite passes; this CLI is the standalone/CI entry.

Examples:

    python tools/proglint.py --model resnet50
    python tools/proglint.py --model resnet50 --fuse --backward
    python tools/proglint.py --model bert --backward
    python tools/proglint.py --program path/to/model_dir   # __model__ inside
    python tools/proglint.py --model resnet18 --json --werror
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNETS = ("resnet18", "resnet34", "resnet50", "resnet101", "resnet152")
MODELS = RESNETS + ("bert",)


def build_bench_model(model: str, batch: int = 2, image_size: int = 64,
                      seq: int = 64, max_preds: int = 8):
    """Build one bench model's train graph (shared model-builder
    plumbing: proglint lints it, proftop profiles it). Returns
    (main, startup, feeds, loss, cfg). Tiny default shapes: lint/profile
    coverage depends on graph STRUCTURE, not batch size, and CI wants
    this cheap."""
    import paddle_tpu.fluid as fluid

    if model in RESNETS:
        from paddle_tpu.models.resnet import (
            ResNetConfig,
            build_resnet_train_program,
        )

        cfg = getattr(ResNetConfig, model)()
        main, startup, feeds, loss = build_resnet_train_program(
            cfg, batch, image_size, fluid.Program(), fluid.Program())
    elif model == "bert":
        from paddle_tpu.models.bert import (
            BertConfig,
            build_bert_pretrain_program,
        )

        cfg = BertConfig()
        main, startup, feeds, loss = build_bert_pretrain_program(
            cfg, batch, seq, max_preds)
    else:
        raise SystemExit(
            f"unknown --model {model!r} (choose from {', '.join(MODELS)})")
    return main, startup, feeds, loss, cfg


def _build_model(args):
    """Returns [(label, program, live_out)] for the requested model."""
    main, startup, feeds, loss, _cfg = build_bench_model(
        args.model, args.batch, args.image_size, args.seq, args.max_preds)

    if args.fuse:
        from paddle_tpu.fluid.fusion_pass import apply_conv_bn_fusion

        n = apply_conv_bn_fusion(main)
        print(f"# conv_bn_fusion: {n} triple(s) fused", file=sys.stderr)
    if args.backward:
        from paddle_tpu.fluid.backward import append_backward

        append_backward(loss)
    live = set(feeds) | {loss.name}
    return [(f"{args.model}:main", main, live),
            (f"{args.model}:startup", startup, set())]


def _load_program(path):
    from paddle_tpu.fluid import io as fio

    meta_live = set()
    if os.path.isdir(path):
        meta_path = os.path.join(path, "__meta__.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                m = json.load(f)
            meta_live = set(m.get("feed_names", ())) | set(
                m.get("fetch_names", ()))
        for cand in ("__model__", "__train_model__"):
            p = os.path.join(path, cand)
            if os.path.exists(p):
                path = p
                break
        else:
            raise SystemExit(f"{path}: no __model__ file in directory")
    with open(path, "rb") as f:
        data = f.read()
    if os.path.basename(path) == "__train_model__":
        import pickle

        meta = pickle.loads(data)
        live = set(meta.get("feed_names", ())) | {meta.get("loss_name")}
        live = {n for n in live if n}
        return [(f"{path}:main", fio._deserialize_program(meta["main"]),
                 live),
                (f"{path}:startup",
                 fio._deserialize_program(meta["startup"]), set())]
    return [(path, fio._deserialize_program(data), meta_live)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="proglint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--model", help="bench model to build and lint: "
                     f"{', '.join(RESNETS + ('bert',))}")
    src.add_argument("--program", help="saved program (__model__ pickle "
                     "or a dir containing one)")
    ap.add_argument("--backward", action="store_true",
                    help="append_backward on the model's loss before "
                    "linting (grad-graph checks get a real graph)")
    ap.add_argument("--fuse", action="store_true",
                    help="apply conv+BN fusion before linting")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--max-preds", type=int, default=8)
    ap.add_argument("--checks", help="comma-separated subset of checks "
                    "(default: all registered)")
    ap.add_argument("--live-out", help="comma-separated extra names to "
                    "treat as live (fetch targets)")
    ap.add_argument("--werror", action="store_true",
                    help="exit nonzero on warnings too")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per finding on stdout")
    args = ap.parse_args(argv)

    from paddle_tpu.fluid.analysis import (
        ERROR,
        WARNING,
        all_checks,
        format_findings,
        verify_program,
    )

    checks = args.checks.split(",") if args.checks else None
    if checks:
        bad = [c for c in checks if c not in all_checks()]
        if bad:
            raise SystemExit(f"unknown check(s) {bad}; "
                             f"registered: {all_checks()}")
    extra_live = set(filter(None, (args.live_out or "").split(",")))

    targets = (_build_model(args) if args.model
               else _load_program(args.program))
    n_err = n_warn = 0
    for label, program, live in targets:
        findings = verify_program(program, checks=checks,
                                  live_out=live | extra_live)
        n_err += sum(1 for f in findings if f.severity == ERROR)
        n_warn += sum(1 for f in findings if f.severity == WARNING)
        if args.json:
            for f in findings:
                print(json.dumps({
                    "target": label, "check": f.check,
                    "severity": f.severity, "message": f.message,
                    "block": f.block_idx, "op_index": f.op_index,
                    "op_type": f.op_type, "var": f.var,
                    "pass": f.pass_name,
                }))
        else:
            print(f"== {label}: "
                  f"{len(program.global_block().ops)} root ops, "
                  f"{len(findings)} finding(s)")
            if findings:
                print(format_findings(findings))
    failed = n_err > 0 or (args.werror and n_warn > 0)
    print(f"proglint: {n_err} error(s), {n_warn} warning(s) -> "
          f"{'FAIL' if failed else 'OK'}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
