#!/usr/bin/env python
"""proglint — static lint for Program IR graphs (fluid/analysis).

Lints a bench-model program built in-process, or any saved program
(`__model__` pickle written by fluid.io.save_inference_model /
save_train_model), and exits nonzero on error-severity findings. The
same checks run flag-gated inside the Executor (FLAGS_program_verify)
and around the rewrite passes; this CLI is the standalone/CI entry.

Cross-program contracts (fluid/analysis/crosscheck.py) ride along for
free where the inputs allow: a `__train_model__` lints its startup/main
pairing, and `--pair` builds the bench model's for_test eval clone and
verifies the train/eval contract too.

`--fix` applies the mechanical fixers (fluid/analysis/fixes.py): torn
@GRAD chains dropped, dead ops/vars swept, stale last-writer links
relinked, missing startup initializers inserted — each re-verified so a
fix that introduces a NEW error aborts attributed to it. With
`--in-place` the repaired program is written back into the saved
`__model__` / `__train_model__` pickle.

Examples:

    python tools/proglint.py --model resnet50
    python tools/proglint.py --model resnet50 --fuse --backward
    python tools/proglint.py --model bert --backward
    python tools/proglint.py --model resnet18 --backward --pair
    python tools/proglint.py --program path/to/model_dir   # __model__ inside
    python tools/proglint.py --program dir --fix --in-place
    python tools/proglint.py --model resnet18 --json --werror
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNETS = ("resnet18", "resnet34", "resnet50", "resnet101", "resnet152")
MODELS = RESNETS + ("bert",)


def build_bench_model(model: str, batch: int = 2, image_size: int = 64,
                      seq: int = 64, max_preds: int = 8):
    """Build one bench model's train graph (shared model-builder
    plumbing: proglint lints it, proftop profiles it). Returns
    (main, startup, feeds, loss, cfg). Tiny default shapes: lint/profile
    coverage depends on graph STRUCTURE, not batch size, and CI wants
    this cheap."""
    import paddle_tpu.fluid as fluid

    if model in RESNETS:
        from paddle_tpu.models.resnet import (
            ResNetConfig,
            build_resnet_train_program,
        )

        cfg = getattr(ResNetConfig, model)()
        main, startup, feeds, loss = build_resnet_train_program(
            cfg, batch, image_size, fluid.Program(), fluid.Program())
    elif model == "bert":
        from paddle_tpu.models.bert import (
            BertConfig,
            build_bert_pretrain_program,
        )

        cfg = BertConfig()
        main, startup, feeds, loss = build_bert_pretrain_program(
            cfg, batch, seq, max_preds)
    else:
        raise SystemExit(
            f"unknown --model {model!r} (choose from {', '.join(MODELS)})")
    return main, startup, feeds, loss, cfg


def _target(label, program, live, startup=None, eval_program=None,
            feed_names=(), save_fn=None):
    return {"label": label, "program": program, "live": set(live),
            "startup": startup, "eval": eval_program,
            "feed_names": list(feed_names), "save_fn": save_fn}


def _build_model(args):
    """Returns lint targets for the requested bench model."""
    main, startup, feeds, loss, _cfg = build_bench_model(
        args.model, args.batch, args.image_size, args.seq, args.max_preds)

    eval_prog = None
    if args.pair:
        # the canonical eval clone is taken from the FORWARD graph
        # (hapi clones before minimize; clone(for_test=True) does not
        # prune a backward that already ran)
        eval_prog = main.clone(for_test=True)
    if args.fuse:
        from paddle_tpu.fluid.fusion_pass import apply_conv_bn_fusion

        n = apply_conv_bn_fusion(main)
        print(f"# conv_bn_fusion: {n} triple(s) fused", file=sys.stderr)
    if args.backward:
        from paddle_tpu.fluid.backward import append_backward

        append_backward(loss)
    live = set(feeds) | {loss.name}
    return [_target(f"{args.model}:main", main, live, startup=startup,
                    eval_program=eval_prog, feed_names=feeds),
            _target(f"{args.model}:startup", startup, set())]


def _load_program(path):
    from paddle_tpu.fluid import io as fio

    meta_live = set()
    if os.path.isdir(path):
        meta_path = os.path.join(path, "__meta__.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                m = json.load(f)
            meta_live = set(m.get("feed_names", ())) | set(
                m.get("fetch_names", ()))
        for cand in ("__model__", "__train_model__"):
            p = os.path.join(path, cand)
            if os.path.exists(p):
                path = p
                break
        else:
            raise SystemExit(f"{path}: no __model__ file in directory")
    with open(path, "rb") as f:
        data = f.read()
    if os.path.basename(path) == "__train_model__":
        import pickle

        meta = pickle.loads(data)
        main = fio._deserialize_program(meta["main"])
        startup = fio._deserialize_program(meta["startup"])
        feeds = list(meta.get("feed_names", ()))
        live = set(feeds) | {meta.get("loss_name")}
        live = {n for n in live if n}

        def save_train(main=main, startup=startup, meta=meta, path=path):
            meta = dict(meta)
            meta["main"] = fio._serialize_program(main)
            meta["startup"] = fio._serialize_program(startup)
            fio._atomic_write_bytes(path, pickle.dumps(meta))

        return [_target(f"{path}:main", main, live, startup=startup,
                        feed_names=feeds, save_fn=save_train),
                _target(f"{path}:startup", startup, set())]

    program = fio._deserialize_program(data)

    def save_model(program=program, path=path):
        fio._atomic_write_bytes(path, fio._serialize_program(program))

    return [_target(path, program, meta_live, feed_names=meta_live,
                    save_fn=save_model)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="proglint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--model", help="bench model to build and lint: "
                     f"{', '.join(RESNETS + ('bert',))}")
    src.add_argument("--program", help="saved program (__model__ pickle "
                     "or a dir containing one)")
    ap.add_argument("--backward", action="store_true",
                    help="append_backward on the model's loss before "
                    "linting (grad-graph checks get a real graph)")
    ap.add_argument("--fuse", action="store_true",
                    help="apply conv+BN fusion before linting")
    ap.add_argument("--pair", action="store_true",
                    help="build the for_test eval clone and verify the "
                    "train/eval contract too (bench models only)")
    ap.add_argument("--fix", action="store_true",
                    help="apply the mechanical fixers before linting "
                    "(torn grads, dead code, stale links, missing "
                    "startup inits)")
    ap.add_argument("--in-place", action="store_true",
                    help="with --fix on a saved program: write the "
                    "repaired program back into the pickle")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--max-preds", type=int, default=8)
    ap.add_argument("--checks", help="comma-separated subset of checks "
                    "(default: all registered)")
    ap.add_argument("--live-out", help="comma-separated extra names to "
                    "treat as live (fetch targets)")
    ap.add_argument("--werror", action="store_true",
                    help="exit nonzero on warnings too")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per finding on stdout")
    args = ap.parse_args(argv)
    if args.in_place and not args.fix:
        ap.error("--in-place requires --fix")
    if args.in_place and not args.program:
        ap.error("--in-place only applies to --program (saved pickles)")

    from paddle_tpu.fluid.analysis import (
        ERROR,
        WARNING,
        all_checks,
        apply_fixes,
        format_findings,
        verify_pair,
        verify_program,
    )

    checks = args.checks.split(",") if args.checks else None
    if checks:
        bad = [c for c in checks if c not in all_checks()]
        if bad:
            raise SystemExit(f"unknown check(s) {bad}; "
                             f"registered: {all_checks()}")
    extra_live = set(filter(None, (args.live_out or "").split(",")))

    targets = (_build_model(args) if args.model
               else _load_program(args.program))
    n_err = n_warn = 0
    for t in targets:
        label, program, live = t["label"], t["program"], t["live"]
        if args.fix:
            reports = apply_fixes(program, live_out=live | extra_live,
                                  startup=t["startup"],
                                  feed_names=t["feed_names"])
            for r in reports:
                for line in r.actions:
                    print(f"# fix[{r.name}] {label}: {line}",
                          file=sys.stderr)
            if args.in_place and t["save_fn"] and any(
                    r.changed for r in reports):
                t["save_fn"]()
                print(f"# fix: wrote repaired program back to {label}",
                      file=sys.stderr)
        findings = verify_program(program, checks=checks,
                                  live_out=live | extra_live)
        if t["startup"] is not None or t["eval"] is not None:
            findings = findings + verify_pair(
                program, startup=t["startup"], eval_program=t["eval"],
                feed_names=t["feed_names"])
        n_err += sum(1 for f in findings if f.severity == ERROR)
        n_warn += sum(1 for f in findings if f.severity == WARNING)
        if args.json:
            for f in findings:
                print(json.dumps({
                    "target": label, "check": f.check,
                    "severity": f.severity, "message": f.message,
                    "block": f.block_idx, "op_index": f.op_index,
                    "op_type": f.op_type, "var": f.var,
                    "pass": f.pass_name,
                }))
        else:
            print(f"== {label}: "
                  f"{len(program.global_block().ops)} root ops, "
                  f"{len(findings)} finding(s)")
            if findings:
                print(format_findings(findings))
    failed = n_err > 0 or (args.werror and n_warn > 0)
    print(f"proglint: {n_err} error(s), {n_warn} warning(s) -> "
          f"{'FAIL' if failed else 'OK'}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
