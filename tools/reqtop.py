#!/usr/bin/env python
"""reqtop: reconstruct where each serving request's wall time went
(ISSUE 19).

Input: a directory of `flightrec.<tag>.json` flight-recorder dumps
(telemetry/tracing.py) from the serving CLIENT process and every
serving REPLICA — the same files tracetop merges, read request-first
instead of round-first. Dumps are merged by the wire-propagated
trace_id, so one generation shows up as ONE record spanning the
client's `generate`/`generate_stream` root, each replica's RPC hops,
and each engine residency's `gen_request` umbrella with its
queue_wait / prefill / per-decode-step / lifecycle-event children —
including BOTH replicas of a mid-stream failover resume.

Per request reqtop reports:

  client_ms      the caller-observed wall time (the root span)
  residencies    one row per engine residency (per replica): queue
                 wait, prefill (positions / cached / prefix-hit),
                 decode wall + pro-rata charged ms + step count,
                 peer-prefill bubbles, preempt/resume/evict/
                 weight_fence events, and the attributed fraction of
                 the residency's wall time (the >=90% acceptance bar)
  slow steps     the decode steps that cost the most (their `step`
                 index names the co-batched victims of a stall)

Usage:
  python tools/reqtop.py <trace_dir>              # slowest-first report
  python tools/reqtop.py <trace_dir> --json       # machine-readable
  python tools/reqtop.py <trace_dir> --topk 5     # only the 5 slowest
  python tools/reqtop.py <trace_dir> --trace ID   # one request in full
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

# client roots and the engine umbrella that anchor a serving trace
_CLIENT_ROOTS = ("generate", "generate_stream")
_ENGINE_SPAN = "gen_request"
# residency children summed into the attribution numerator
_ATTRIBUTED = ("queue_wait", "prefill", "decode_step", "peer_prefill")
_EVENTS = ("preempt", "resume", "evict", "weight_fence")


def load_dumps(directory: str) -> List[dict]:
    """Every parseable flightrec.<tag>.json in `directory` (unreadable
    files are skipped with a warning — a torn dump from a crashing
    replica must not cost the survivors' report)."""
    dumps = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "flightrec.*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[reqtop] skipping unreadable dump {path}: {e}",
                  file=sys.stderr)
            continue
        if isinstance(d, dict) and isinstance(d.get("spans"), list):
            dumps.append(d)
    return dumps


def merged_spans(dumps: List[dict]) -> List[dict]:
    """All spans across dumps, stamped with the dump's process tag (a
    span's own `proc` wins when present), time-ordered."""
    out = []
    for d in dumps:
        tag = d.get("process", "?")
        for s in d["spans"]:
            s = dict(s)
            s.setdefault("proc", tag)
            out.append(s)
    out.sort(key=lambda s: s.get("ts", 0.0))
    return out


def merged_requests(dumps: List[dict]) -> Dict[str, List[dict]]:
    """Per-request engine flight records (tracing.note_request), keyed
    by trace id — the engine's own completion ledger, joined onto the
    span reconstruction."""
    out: Dict[str, List[dict]] = {}
    for d in dumps:
        for rec in d.get("requests") or []:
            tid = rec.get("trace")
            if tid:
                out.setdefault(tid, []).append(dict(rec))
    return out


def _residency(umbrella: dict, spans: List[dict]) -> dict:
    """Break one engine residency (a gen_request span + its children)
    into attributed buckets."""
    kids = [s for s in spans if s.get("parent") == umbrella["span"]]
    buckets = {k: 0.0 for k in _ATTRIBUTED}
    steps: List[dict] = []
    events: List[dict] = []
    charged = 0.0
    # the retiring decode_step span closes a beat AFTER the umbrella
    # (the result event fires mid-step): clip every child to the
    # residency window so attributed_ms can never exceed wall_ms
    u0 = umbrella.get("ts") or 0.0
    u1 = u0 + (umbrella.get("dur_ms") or 0.0) / 1e3

    def _clipped(c: dict) -> float:
        d = c.get("dur_ms") or 0.0
        c0 = c.get("ts")
        if c0 is None or not u1:
            return d
        return max(0.0, (min(c0 + d / 1e3, u1) - max(c0, u0)) * 1e3)

    for c in kids:
        name = c["name"]
        if name in buckets:
            buckets[name] += _clipped(c)
        if name == "decode_step":
            a = c.get("attrs") or {}
            full = c.get("dur_ms") or 0.0
            frac = (_clipped(c) / full) if full > 0 else 1.0
            charged += float(a.get("charged_ms") or 0.0) * frac
            steps.append({"step": a.get("step"), "ms": c.get("dur_ms"),
                          "charged_ms": a.get("charged_ms"),
                          "batch": a.get("batch"),
                          "status": c.get("status", "ok")})
        elif name in _EVENTS:
            events.append({"event": name, "ts": c.get("ts"),
                           **(c.get("attrs") or {})})
    wall = umbrella.get("dur_ms") or 0.0
    attributed = sum(buckets.values())
    a = umbrella.get("attrs") or {}
    prefill = next((s for s in kids if s["name"] == "prefill"), None)
    return {
        "proc": umbrella.get("proc", "?"),
        "trace": umbrella.get("trace"),
        "wall_ms": round(wall, 3),
        "outcome": a.get("outcome", umbrella.get("status", "ok")),
        "resume": bool(a.get("resume")),
        "tokens": a.get("tokens"),
        "queue_wait_ms": round(buckets["queue_wait"], 3),
        "prefill_ms": round(buckets["prefill"], 3),
        "prefill_attrs": (prefill.get("attrs") if prefill else None),
        "decode_ms": round(buckets["decode_step"], 3),
        "decode_charged_ms": round(charged, 3),
        "decode_steps": len(steps),
        "peer_prefill_ms": round(buckets["peer_prefill"], 3),
        "events": events,
        "attributed_ms": round(attributed, 3),
        "attributed_frac": (round(attributed / wall, 4) if wall > 0
                            else None),
        "slowest_steps": sorted(steps,
                                key=lambda s: -(s["ms"] or 0.0))[:3],
    }


def requests_report(spans: List[dict],
                    records: Optional[Dict[str, List[dict]]] = None
                    ) -> List[dict]:
    """One record per serving trace, slowest-first: the client root,
    every engine residency's attribution breakdown, and the engine's
    own flight records when present."""
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    out = []
    for tid, ss in by_trace.items():
        umbrellas = [s for s in ss if s["name"] == _ENGINE_SPAN]
        roots = [s for s in ss if s["name"] in _CLIENT_ROOTS]
        if not umbrellas and not roots:
            continue  # not a serving trace
        umbrellas.sort(key=lambda s: s.get("ts", 0.0))
        residencies = [_residency(u, ss) for u in umbrellas]
        root = roots[0] if roots else None
        client_ms = root.get("dur_ms") if root else None
        total = (client_ms if client_ms is not None
                 else sum(r["wall_ms"] for r in residencies))
        rec = {
            "trace": tid,
            "root": (root["name"] if root else _ENGINE_SPAN),
            "client_proc": (root.get("proc") if root else None),
            "client_ms": client_ms,
            "failovers": ((root.get("attrs") or {}).get("failovers")
                          if root else None),
            "total_ms": round(total or 0.0, 3),
            "n_residencies": len(residencies),
            "residencies": residencies,
            "procs": sorted({s.get("proc", "?") for s in ss}),
        }
        if records:
            rec["flight_records"] = records.get(tid) or []
        out.append(rec)
    out.sort(key=lambda r: -(r["total_ms"] or 0.0))
    return out


def format_request(r: dict) -> str:
    head = (f"trace {str(r['trace'])[:16]} root={r['root']} "
            f"{r['total_ms']:.1f}ms total, "
            f"{r['n_residencies']} engine residenc"
            f"{'y' if r['n_residencies'] == 1 else 'ies'}"
            f" ({', '.join(r['procs'])})")
    if r.get("failovers"):
        head += f" failovers={r['failovers']}"
    lines = [head]
    for res in r["residencies"]:
        frac = res["attributed_frac"]
        lines.append(
            f"  [{res['proc']}] {res['outcome']}"
            f"{' (resume)' if res['resume'] else ''}: "
            f"wall={res['wall_ms']:.1f}ms = "
            f"queue {res['queue_wait_ms']:.1f} + "
            f"prefill {res['prefill_ms']:.1f} + "
            f"decode {res['decode_ms']:.1f} "
            f"(charged {res['decode_charged_ms']:.1f} over "
            f"{res['decode_steps']} steps) + "
            f"peer_prefill {res['peer_prefill_ms']:.1f}  "
            f"[attributed "
            f"{('%.0f%%' % (100 * frac)) if frac is not None else '?'}]")
        for ev in res["events"]:
            kv = " ".join(f"{k}={v}" for k, v in ev.items()
                          if k not in ("event", "ts"))
            lines.append(f"      event {ev['event']} {kv}")
        for st in res["slowest_steps"]:
            if st["ms"] and st["ms"] >= 2 * max(
                    1e-9, res["decode_ms"] / max(1, res["decode_steps"])):
                lines.append(
                    f"      slow step {st['step']}: {st['ms']:.1f}ms "
                    f"(charged {st['charged_ms']}, "
                    f"batch {st['batch']})")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="reqtop",
        description="merge client+replica flight-recorder dumps by "
                    "trace_id; reconstruct where each serving "
                    "request's wall time went")
    p.add_argument("trace_dir", help="directory of flightrec.<tag>.json "
                                     "dumps (PADDLE_TRACE_DIR)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--topk", type=int, default=0,
                   help="only the K slowest requests")
    p.add_argument("--trace", default=None,
                   help="only this trace id (prefix match)")
    args = p.parse_args(argv)

    dumps = load_dumps(args.trace_dir)
    if not dumps:
        print(f"[reqtop] no flightrec.*.json dumps in "
              f"{args.trace_dir!r} — run with PADDLE_TRACING=1 and "
              f"PADDLE_TRACE_DIR set on the client and every replica",
              file=sys.stderr)
        return 1
    spans = merged_spans(dumps)
    reqs = requests_report(spans, merged_requests(dumps))
    if args.trace:
        reqs = [r for r in reqs
                if str(r["trace"]).startswith(args.trace)]
    if args.topk:
        reqs = reqs[:args.topk]
    if args.json:
        json.dump({"processes": sorted({d.get("process", "?")
                                        for d in dumps}),
                   "n_spans": len(spans),
                   "requests": reqs}, sys.stdout, default=str)
        print()
        return 0
    print(f"[reqtop] {len(dumps)} process dumps, {len(spans)} spans, "
          f"{len(reqs)} serving requests (slowest first)")
    for r in reqs:
        print(format_request(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
