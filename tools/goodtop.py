#!/usr/bin/env python
"""goodtop — job-lifetime goodput/badput summary (telemetry/goodput.py;
the fleet-side sibling of proftop/memtop/numtop/tracetop).

Reads the per-incarnation goodput ledgers a PADDLE_GOODPUT=1 job wrote
(`goodput.<tag>.<incarnation>.jsonl` under PADDLE_GOODPUT_DIR /
PADDLE_TRACE_DIR, plus the launcher's `goodput.launcher.jsonl`
lifecycle events) and renders the question the per-rank planes cannot
answer: what fraction of the JOB's wall-clock was productive training,
and where did the rest go — across every rank, restart and eviction.

  default       job summary: goodput %, per-bucket seconds + share,
                unclassified residual (must stay < 2%% on a healthy
                stitch)
  --by-rank     one row per rank tag (incarnations, steps, goodput %,
                worst badput bucket)
  --incidents   per-restart cost breakdown — each death decomposed into
                detection / respawn / recompile / replay seconds (the
                launcher ledger supplies detect/respawn timestamps) —
                plus straggler stall episodes with the culprit's step
                trace_id (feed it to tools/tracetop.py)
  --json        the full stitched view as one JSON object

Examples:

    python tools/goodtop.py /tmp/job_traces
    python tools/goodtop.py /tmp/job_traces --by-rank --incidents
    python tools/goodtop.py --json            # dir from PADDLE_GOODPUT_DIR
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.telemetry import goodput  # noqa: E402

BAR_W = 30


def _fmt_s(v) -> str:
    return "-" if v is None else f"{v:.2f}s"


def _pct(part: float, total: float) -> str:
    return f"{100.0 * part / total:5.1f}%" if total > 0 else "    -"


def render_summary(view: dict, out) -> None:
    job = view["job"]
    total = job["total_s"]
    ratio = job.get("goodput_ratio")
    print("== goodtop: job-lifetime goodput ==", file=out)
    print(f"ranks: {len(view['ranks'])}   classified wall: "
          f"{total:.2f}s   goodput: "
          f"{'-' if ratio is None else f'{100 * ratio:.1f}%'}   "
          f"unclassified residual: "
          f"{100 * job.get('unclassified_frac', 0):.2f}%", file=out)
    buckets = {}
    for row in view["ranks"].values():
        for b, v in row["buckets_s"].items():
            buckets[b] = buckets.get(b, 0.0) + v
    print(f"{'bucket':<18} {'seconds':>10} {'share':>7}", file=out)
    for b in goodput.BUCKETS:
        v = buckets.get(b, 0.0)
        if v <= 0 and b != "productive_step":
            continue
        bar = "#" * int(BAR_W * v / total) if total > 0 else ""
        print(f"{b:<18} {v:>10.2f} {_pct(v, total):>7}  {bar}", file=out)


def render_by_rank(view: dict, out) -> None:
    print("\n== per-rank ==", file=out)
    print(f"{'tag':<12} {'incs':>4} {'steps':>6} {'wall':>9} "
          f"{'goodput':>8} {'worst badput':<24}", file=out)
    for tag, row in sorted(view["ranks"].items()):
        worst = sorted(
            ((b, v) for b, v in row["buckets_s"].items()
             if b != "productive_step" and v > 0),
            key=lambda kv: -kv[1])
        worst_s = (f"{worst[0][0]} ({worst[0][1]:.2f}s)"
                   if worst else "-")
        ratio = row.get("goodput_ratio")
        print(f"{tag:<12} {row['incarnations']:>4} {row['n_steps']:>6} "
              f"{row['wall_s']:>8.2f}s "
              f"{'-' if ratio is None else f'{100 * ratio:6.1f}%':>8} "
              f"{worst_s:<24}", file=out)


def render_incidents(view: dict, out) -> None:
    print("\n== incidents (costliest first) ==", file=out)
    if not view["incidents"]:
        print("(none)", file=out)
        return
    for inc in view["incidents"]:
        if inc.get("kind") == "restart":
            print(f"restart  {inc['tag']} inc{inc['from_incarnation']}->"
                  f"inc{inc['to_incarnation']}  gap {inc['gap_s']:.2f}s"
                  f"  reason: {inc.get('reason') or '?'}"
                  + (f"  culprit: {inc['culprit']}"
                     if inc.get("culprit") else ""), file=out)
            print(f"         detection {_fmt_s(inc.get('detection_s'))}"
                  f" -> respawn {_fmt_s(inc.get('respawn_s'))}"
                  f" -> recompile {_fmt_s(inc.get('recompile_s'))}"
                  f" (+restore {_fmt_s(inc.get('restore_s'))})"
                  f" -> replay {_fmt_s(inc.get('replay_s'))}"
                  f" ({inc.get('replay_steps', 0)} steps)", file=out)
        elif inc.get("kind") == "stall":
            print(f"stall    rank {inc.get('rank')}"
                  f" ({inc.get('tag') or '?'})  step {inc.get('step')}"
                  f"  +{(inc.get('excess_ms') or 0) / 1e3:.2f}s vs median"
                  f"  cause: {inc.get('cause', '?')}"
                  + (f"  trace: {inc['trace_id']}"
                     if inc.get("trace_id") else ""), file=out)
        elif inc.get("kind") == "coord_outage":
            gap = inc.get("gap_s")
            print(f"coord    control-plane outage  gap "
                  f"{f'{gap:.2f}s' if gap is not None else '?'}",
                  file=out)
            print(f"         no rank died: trainers rode it out in "
                  f"grace mode; coordinator back at incarnation "
                  f"{inc.get('incarnation', '?')}", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="goodtop", description="job-lifetime goodput summary")
    p.add_argument("dir", nargs="?", default=None,
                   help="ledger directory (default: PADDLE_GOODPUT_DIR "
                        "or PADDLE_TRACE_DIR or .)")
    p.add_argument("--by-rank", action="store_true")
    p.add_argument("--incidents", action="store_true")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)

    directory = (args.dir or os.environ.get("PADDLE_GOODPUT_DIR")
                 or os.environ.get("PADDLE_TRACE_DIR") or ".")
    if not os.path.isdir(directory):
        print(f"goodtop: no such directory: {directory}", file=sys.stderr)
        return 2
    view = goodput.stitch_job(directory)
    if not view["ranks"]:
        print(f"goodtop: no goodput.<tag>.<inc>.jsonl ledgers in "
              f"{directory} (arm the job with PADDLE_GOODPUT=1 or "
              f"launch.py --fleetz_port)", file=sys.stderr)
        return 1
    if args.as_json:
        json.dump(view, sys.stdout, indent=1, default=str)
        print()
        return 0
    render_summary(view, sys.stdout)
    if args.by_rank:
        render_by_rank(view, sys.stdout)
    if args.incidents:
        render_incidents(view, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
