#!/usr/bin/env python
"""memtop — per-op / per-variable HBM attribution for Program IR graphs
(telemetry/memory.py; the memory-side sibling of proftop).

Builds a bench model's train graph, runs the static live-range pass
(fluid/analysis/liverange.py) and — when a backend is available — the
measured join (XLA buffer assignment + optimized-HLO op-scope
attribution), then prints buffers ranked by bytes with user callstacks,
the per-category breakdown (params / optimizer_state / gradients /
feeds / activations), attribution coverage, and the what-if levers.

`--budget <bytes>` turns memtop into a gate: exit 2 when the static
peak estimate exceeds the budget — the hook CI and the autotuner's
feasibility pre-check both consume this (a candidate that cannot fit
VMEM/HBM must be rejected before it is ever timed).

Examples:

    python tools/memtop.py --model resnet50
    python tools/memtop.py --model bert --json --topk 10
    python tools/memtop.py --model bert --budget 8000000000  # 8 GB gate
    python tools/memtop.py --model resnet18 --static-only
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS_DIR))  # repo root: paddle_tpu
if _TOOLS_DIR not in sys.path:  # tools/: proglint (in-process importers)
    sys.path.insert(0, _TOOLS_DIR)

from proglint import MODELS, build_bench_model  # noqa: E402 — path above

EXIT_OVER_BUDGET = 2


def _random_feed(model, cfg, args):
    import numpy as np

    rng = np.random.RandomState(0)
    if model.startswith("resnet"):
        return {
            "image": rng.rand(args.batch, 3, args.image_size,
                              args.image_size).astype(np.float32),
            "label": rng.randint(0, cfg.num_classes,
                                 (args.batch, 1)).astype(np.int64),
        }
    from paddle_tpu.models.bert import random_pretrain_batch

    return random_pretrain_batch(cfg, args.batch, args.seq, args.max_preds,
                                 seed=0)


def build_report(args):
    """Build the model + optimizer graph and produce the MemoryReport —
    static-only (no backend required), or the full measured join."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.telemetry import memory

    main, startup, feeds, loss, cfg = build_bench_model(
        args.model, args.batch, args.image_size, args.seq, args.max_preds)
    with fluid.program_guard(main, startup):
        if args.model.startswith("resnet"):
            opt = fluid.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9)
        else:
            opt = fluid.optimizer.AdamOptimizer(learning_rate=1e-4)
        opt.minimize(loss)
    feed = _random_feed(args.model, cfg, args)
    if args.static_only:
        return memory.build_memory_report(
            main, feed_shapes=feed, fetch_names=[loss.name],
            model=args.model, budget_bytes=args.budget)
    exe = fluid.Executor()
    exe.run(startup)
    return memory.profile_executor_memory(
        exe, main, feed, [loss], model=args.model,
        budget_bytes=args.budget)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="memtop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", required=True,
                    help=f"bench model to build and size: "
                    f"{', '.join(MODELS)}")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--max-preds", type=int, default=8)
    ap.add_argument("--topk", type=int, default=20)
    ap.add_argument("--budget", type=int, default=None,
                    help="HBM budget in BYTES: exit "
                    f"{EXIT_OVER_BUDGET} when the static peak estimate "
                    "exceeds it (the CI / autotuner feasibility gate)")
    ap.add_argument("--static-only", action="store_true",
                    help="skip the measured join (no compile, no "
                    "backend needed): live-range pass only")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object (the full report) on stdout")
    args = ap.parse_args(argv)

    report = build_report(args)
    if args.json:
        print(json.dumps(report.to_json(args.topk)))
    else:
        print(report.format_table(args.topk))
    if not report.static.buffers:
        print("memtop: no sized buffers (empty program?)",
              file=sys.stderr)
        return 1
    if report.over_budget():
        print(f"memtop: static peak estimate "
              f"{report.static.peak_bytes} B exceeds --budget "
              f"{args.budget} B", file=sys.stderr)
        return EXIT_OVER_BUDGET
    return 0


if __name__ == "__main__":
    sys.exit(main())
