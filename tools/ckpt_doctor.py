#!/usr/bin/env python
"""ckpt_doctor — fsck for CheckpointManager roots (fluid/checkpoint.py).

Walks a checkpoint root, verifies the manifest chain of every step —
single-writer manifests, and for sharded layouts the global manifest,
every shard's manifest sha256, and every content file's size + sha256 —
and classifies each step dir:

  OK       fully committed and every checksum matches
  TORN     never committed: no (global) manifest — a crash between the
           content writes and the commit point, or between two ranks'
           shard commits. Invisible to restore() by construction.
  CORRUPT  committed but failing verification (bit rot, short write):
           restore() skips it with a warning.

plus ORPHANS: stray `.tmp-ckpt-*` work dirs and `rank<k>/` shard dirs a
global manifest does not list (leftovers of an elastic resize or a
superseded save).

  --gc      remove torn dirs, orphans, and corrupt dirs that a newer or
            equal OK step supersedes (the newest data on disk is never
            deleted, even when it is corrupt — repair it instead)
  --repair  re-fetch a corrupt PS-table shard (`<table>.pkl`) from a
            live replica (replication R>=2) via the primary's
            `fetch_replica_state` RPC, rewrite the file, and re-commit
            the manifest (and global-manifest shard sha) around it
  --json    machine-readable report

Endpoints for --repair come from --endpoints or
PADDLE_PSERVERS_IP_PORT_LIST. Exit status: 0 when every remaining step
is OK, 1 otherwise.

Run it offline (no writer active on the root): --gc removing a torn dir
that an in-flight save is still building would erase work in progress.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import pickle
import re
import shutil
import sys
from typing import Dict, List, Optional

MANIFEST = "manifest.json"
GLOBAL_MANIFEST = "global_manifest.json"
MANIFEST_FORMAT = 1
_DIR_RE = re.compile(r"^ckpt-(\d+)$")
_TMP_RE = re.compile(r"^\.tmp-ckpt-(\d+)-(?:r\d+-)?(\d+)$")
_RANK_RE = re.compile(r"^rank(\d+)$")
# core content files a repair must never synthesize from a PS replica
_CORE_FILES = ("state.pkl", "rng.pkl", "extra.pkl")


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _read_manifest(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            m = json.load(f)
        return m if m.get("format") == MANIFEST_FORMAT else None
    except (OSError, ValueError):
        return None


def _atomic_write(path: str, blob: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _check_files(d: str, files: Dict[str, dict], rel_prefix: str,
                 problems: List[dict]) -> None:
    for rel, meta in sorted(files.items()):
        p = os.path.join(d, rel)
        label = rel_prefix + rel
        if not os.path.exists(p):
            problems.append({"kind": "missing", "file": label})
            continue
        if os.path.getsize(p) != meta["bytes"]:
            problems.append({"kind": "size", "file": label})
            continue
        if _sha256_file(p) != meta["sha256"]:
            problems.append({"kind": "checksum", "file": label})


def _scan_step(path: str, step: int) -> dict:
    entry = {"step": step, "path": path, "sharded": False,
             "status": "ok", "problems": [], "orphan_shards": []}
    problems: List[dict] = entry["problems"]

    gm = _read_manifest(os.path.join(path, GLOBAL_MANIFEST))
    rank_dirs = sorted(n for n in os.listdir(path) if _RANK_RE.match(n))
    if gm is not None:
        entry["sharded"] = True
        shards = gm.get("shards") or {}
        if len(shards) != int(gm.get("world_size") or 0):
            problems.append({"kind": "shard_count",
                             "file": GLOBAL_MANIFEST})
        for rname in sorted(shards):
            info = shards[rname]
            man_path = os.path.join(path, rname, MANIFEST)
            try:
                with open(man_path, "rb") as f:
                    blob = f.read()
            except OSError:
                problems.append({"kind": "missing",
                                 "file": f"{rname}/{MANIFEST}"})
                continue
            if hashlib.sha256(blob).hexdigest() != \
                    info.get("manifest_sha256"):
                problems.append({"kind": "manifest_sha",
                                 "file": f"{rname}/{MANIFEST}"})
                continue
            m = _read_manifest(man_path)
            if m is None:
                problems.append({"kind": "unparseable",
                                 "file": f"{rname}/{MANIFEST}"})
                continue
            _check_files(os.path.join(path, rname), m.get("files", {}),
                         f"{rname}/", problems)
        entry["orphan_shards"] = [
            os.path.join(path, n) for n in rank_dirs
            if n not in shards]
        entry["status"] = "corrupt" if problems else "ok"
        return entry

    if rank_dirs:
        # sharded layout without a global manifest: torn by definition
        entry["sharded"] = True
        entry["status"] = "torn"
        return entry

    m = _read_manifest(os.path.join(path, MANIFEST))
    if m is None:
        entry["status"] = "torn"
        return entry
    _check_files(path, m.get("files", {}), "", problems)
    entry["status"] = "corrupt" if problems else "ok"
    return entry


def scan_root(root: str) -> dict:
    """Classify every step dir + orphan under `root`."""
    root = os.path.abspath(root)
    steps, orphans = [], []
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        m = _DIR_RE.match(name)
        if m:
            steps.append(_scan_step(path, int(m.group(1))))
        elif _TMP_RE.match(name):
            orphans.append(path)
    ok = [e["step"] for e in steps if e["status"] == "ok"]
    return {"root": root, "steps": steps, "orphans": orphans,
            "newest_valid": max(ok) if ok else None}


def gc_root(root: str, report: Optional[dict] = None) -> List[str]:
    """Remove torn dirs, orphans (tmp work dirs + unlisted shard dirs),
    and corrupt dirs superseded by a >= OK step. The newest data on
    disk survives: a corrupt step NEWER than every OK step is reported,
    not deleted — --repair it."""
    report = report if report is not None else scan_root(root)
    newest_ok = report["newest_valid"]
    removed: List[str] = []
    for e in report["steps"]:
        if e["status"] == "torn":
            shutil.rmtree(e["path"], ignore_errors=True)
            removed.append(e["path"])
        elif e["status"] == "corrupt" and newest_ok is not None \
                and e["step"] <= newest_ok:
            shutil.rmtree(e["path"], ignore_errors=True)
            removed.append(e["path"])
        else:
            for p in e["orphan_shards"]:
                shutil.rmtree(p, ignore_errors=True)
                removed.append(p)
    for p in report["orphans"]:
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    return removed


# ---------------------------------------------------------------------------
# repair: corrupt PS-table shard <- live replica (fetch_replica_state)
# ---------------------------------------------------------------------------


def _fetch_table_state(name: str, endpoints: List[str]):
    """Pull every partition's state from the cluster: for partition p,
    ask each endpoint for `fetch_replica_state(name@p<p>, have_seq=-1)`
    — the explicit full-transfer demand the anti-entropy rejoin path
    uses — until one answers as that partition's primary. Returns the
    per-partition state list, or None when any partition has no live
    primary."""
    from paddle_tpu.distributed.ps_server import _Conn

    states = []
    for p in range(len(endpoints)):
        got = None
        for ep in endpoints:
            try:
                conn = _Conn(ep, deadline=5.0, io_timeout=10.0)
                try:
                    out = conn.call("fetch_replica_state",
                                    key=f"{name}@p{p}", have_seq=-1)
                finally:
                    conn.close()
            except Exception:  # noqa: BLE001 — not primary / dead: next
                continue
            if isinstance(out, dict) and "state" in out:
                got = out["state"]
                break
        if got is None:
            return None
        states.append(got)
    return states


def _recommit_manifest(step_path: str, shard_rel: Optional[str],
                       manifest: dict) -> None:
    """Rewrite a (shard) manifest atomically; for sharded layouts also
    update the global manifest's recorded shard sha256 — the repaired
    checkpoint must verify end to end."""
    d = os.path.join(step_path, shard_rel) if shard_rel else step_path
    blob = json.dumps(manifest, indent=1).encode()
    _atomic_write(os.path.join(d, MANIFEST), blob)
    if shard_rel:
        gm_path = os.path.join(step_path, GLOBAL_MANIFEST)
        gm = _read_manifest(gm_path)
        if gm is not None and shard_rel in (gm.get("shards") or {}):
            gm["shards"][shard_rel]["manifest_sha256"] = \
                hashlib.sha256(blob).hexdigest()
            _atomic_write(gm_path, json.dumps(gm, indent=1).encode())


def repair_root(root: str, endpoints: List[str],
                report: Optional[dict] = None) -> List[str]:
    """Repair corrupt `<table>.pkl` shards from live replicas. Only
    PS-table files are repairable this way — scope state (state.pkl,
    rng.pkl, extra.pkl) exists nowhere else. Returns repaired paths."""
    report = report if report is not None else scan_root(root)
    repaired: List[str] = []
    for e in report["steps"]:
        if e["status"] != "corrupt":
            continue
        for prob in list(e["problems"]):
            rel = prob.get("file", "")
            base = os.path.basename(rel)
            if not base.endswith(".pkl") or base in _CORE_FILES:
                continue
            name = base[:-4]
            states = _fetch_table_state(name, endpoints)
            if states is None:
                print(f"[ckpt_doctor] no live primary answered for "
                      f"table {name!r}; cannot repair {rel}",
                      file=sys.stderr)
                continue
            path = os.path.join(e["path"], rel)
            shard_rel = os.path.dirname(rel) or None
            man_dir = os.path.join(e["path"], shard_rel) \
                if shard_rel else e["path"]
            manifest = _read_manifest(os.path.join(man_dir, MANIFEST))
            if manifest is None or rel.split("/")[-1] not in \
                    manifest.get("files", {}):
                continue
            # preserve the checkpoint's on-disk format: a trainer-side
            # RemoteTable state is {"servers": [...]}; a local table's
            # is the bare state dict (only meaningful with 1 partition)
            try:
                with open(path, "rb") as f:
                    orig = pickle.load(f)
                servers_fmt = isinstance(orig, dict) and "servers" in orig
            except Exception:  # noqa: BLE001 — torn pickle
                servers_fmt = len(endpoints) > 1
            state = {"servers": states} if servers_fmt else states[0]
            blob = pickle.dumps(state)
            _atomic_write(path, blob)
            manifest["files"][base] = {
                "sha256": hashlib.sha256(blob).hexdigest(),
                "bytes": len(blob)}
            _recommit_manifest(e["path"], shard_rel, manifest)
            repaired.append(path)
            print(f"[ckpt_doctor] repaired {rel} in "
                  f"{os.path.basename(e['path'])} from a live replica",
                  file=sys.stderr)
    return repaired


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _print_report(report: dict) -> None:
    print(f"ckpt_doctor: {report['root']}")
    for e in report["steps"]:
        tag = e["status"].upper()
        extra = ""
        if e["sharded"]:
            gm = _read_manifest(os.path.join(e["path"], GLOBAL_MANIFEST))
            n = len((gm or {}).get("shards") or {})
            extra = f" (sharded, {n} shards)" if gm else " (sharded)"
        print(f"  {os.path.basename(e['path'])}  {tag:8s}{extra}")
        for prob in e["problems"]:
            print(f"    {prob['kind']}: {prob['file']}")
        for p in e["orphan_shards"]:
            print(f"    orphan shard: {os.path.basename(p)}")
    for p in report["orphans"]:
        print(f"  orphan: {os.path.basename(p)}")
    nv = report["newest_valid"]
    print(f"newest valid: "
          f"{('ckpt-%08d' % nv) if nv is not None else 'NONE'}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ckpt_doctor",
        description="verify / gc / repair CheckpointManager roots")
    ap.add_argument("root", help="checkpoint root directory")
    ap.add_argument("--gc", action="store_true",
                    help="remove torn/orphaned dirs and superseded "
                         "corrupt ones")
    ap.add_argument("--repair", action="store_true",
                    help="re-fetch corrupt PS-table shards from live "
                         "replicas (needs --endpoints or "
                         "PADDLE_PSERVERS_IP_PORT_LIST)")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated pserver endpoints for --repair")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.root):
        print(f"ckpt_doctor: {args.root!r} is not a directory",
              file=sys.stderr)
        return 2

    report = scan_root(args.root)
    actions = {}
    if args.repair:
        eps = [e.strip() for e in
               (args.endpoints
                or os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
                ).split(",") if e.strip()]
        if not eps:
            print("ckpt_doctor: --repair needs --endpoints or "
                  "PADDLE_PSERVERS_IP_PORT_LIST", file=sys.stderr)
            return 2
        actions["repaired"] = repair_root(args.root, eps, report)
        report = scan_root(args.root)  # re-verify after repair
    if args.gc:
        actions["removed"] = gc_root(args.root, report)
        report = scan_root(args.root)

    if args.as_json:
        print(json.dumps(dict(report, **actions), indent=1))
    else:
        _print_report(report)
        for k, paths in actions.items():
            for p in paths:
                print(f"{k}: {p}")

    bad = [e for e in report["steps"] if e["status"] != "ok"
           or e["orphan_shards"]]
    return 1 if (bad or report["orphans"]) else 0


if __name__ == "__main__":
    sys.exit(main())
