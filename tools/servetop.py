#!/usr/bin/env python
"""servetop — live serving-replica SLO view (the serving-side sibling
of proftop/memtop/numtop).

Scrapes each replica's `stats` verb over the PS RPC transport and
renders the numbers an operator watches during an incident: QPS over
the scrape window, shed rate, queue depth, p50/p99 request latency,
micro-batch occupancy, and the weight epoch (is every replica serving
the same model?).  Replicas with a generation engine attached also get
TOK/S (generated tokens per second), DEC/PRE (decode-vs-prefill
position split — the O(n) health check: decode should track tokens,
not explode quadratically), KVRES (KV page-pool residency), PFXHIT
(prefix-cache page hit rate), and RESUME/PREEMPT (r22 crash-tolerance
counters: generations resumed from a carried prefix — failover or
preemption — and active generations preempted for KV pressure; a
climbing PREEMPT with flat RESUME means preempted work is starving,
not resuming).

Per-request SLO columns (ISSUE 19): TTFT50/TTFT99 and TPOT50/TPOT99
from the first-class `serve_ttft_ms`/`serve_tpot_ms` histograms, and
DEDUP (`serve_gen_dedup_hits_total` — marked retries that reattached
instead of decoding twice).  A replica that predates these stats keys
renders dashes in the new columns; everything it does report keeps its
old column position.

Examples:

    python tools/servetop.py --endpoints 127.0.0.1:8500,127.0.0.1:8501
    python tools/servetop.py --endpoints 127.0.0.1:8500 --watch 2
    python tools/servetop.py --endpoints 127.0.0.1:8500 --json
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional


def scrape(endpoints: List[str], deadline: float = 5.0) -> List[dict]:
    """One `stats` scrape per replica; unreachable replicas get an
    error row instead of killing the page."""
    from paddle_tpu.distributed.ps_server import _Conn

    rows = []
    for ep in endpoints:
        conn = _Conn(ep, deadline=deadline, io_timeout=deadline + 5.0)
        try:
            st = conn.call("stats")
            rows.append({"endpoint": ep, **st})
        except Exception as e:  # noqa: BLE001 — dead replica is a row
            rows.append({"endpoint": ep,
                         "error": f"{type(e).__name__}: {e}"})
        finally:
            conn.close()
    return rows


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{float(v):8.1f}"


def _gen_columns(row: dict, prev_row: Optional[dict],
                 window_s: Optional[float]) -> str:
    """The generation-engine columns: tokens/s (windowed when two
    scrapes exist, else the replica's cumulative rate), the
    decode-vs-prefill position split, KV-pool residency and prefix
    cache hit rate.  Replicas without an engine render dashes."""
    g = row.get("generation")
    if not g:
        return (f"{'-':>7} {'-':>11} {'-':>6} {'-':>6} "
                f"{'-':>6} {'-':>7} {'-':>7} {'-':>7} {'-':>7} "
                f"{'-':>7} {'-':>5}")
    toks = int(g.get("tokens_total", 0))
    if prev_row is not None and window_s:
        prev_toks = int(
            (prev_row.get("generation") or {}).get("tokens_total", 0))
        tok_s = f"{(toks - prev_toks) / window_s:7.1f}"
    else:
        tok_s = f"{float(g.get('tokens_per_s', 0.0)):7.1f}"
    dec = int(g.get("decode_positions_total", 0))
    pre = int(g.get("prefill_positions_total", 0))
    rec = int(g.get("recompute_positions_total", 0))
    split = f"{dec + rec}/{pre}"
    kv = g.get("kv_pool") or {}
    resid = (f"{100.0 * float(kv.get('residency', 0.0)):5.1f}%"
             if kv else f"{'-':>6}")
    hit = (f"{100.0 * float(kv.get('prefix_hit_rate', 0.0)):5.1f}%"
           if kv else f"{'-':>6}")
    res = int(g.get("resumed_total", 0))
    pre_t = int(g.get("preempted_total", 0))
    return (f"{tok_s} {split:>11} {resid:>6} {hit:>6} "
            f"{res:6d} {pre_t:7d} {_slo_columns(g)}")


def _q_col(g: dict, key: str) -> str:
    """One SLO quantile column; a replica that predates the key (old
    stats schema) renders a dash in the same width."""
    v = g.get(key)
    return f"{'-':>7}" if v is None else f"{float(v):7.1f}"


def _slo_columns(g: dict) -> str:
    dedup = g.get("dedup_hits_total")
    dd = f"{'-':>5}" if dedup is None else f"{int(dedup):5d}"
    return (f"{_q_col(g, 'ttft_p50_ms')} {_q_col(g, 'ttft_p99_ms')} "
            f"{_q_col(g, 'tpot_p50_ms')} {_q_col(g, 'tpot_p99_ms')} "
            f"{dd}")


def render(rows: List[dict], prev: Optional[Dict[str, dict]] = None,
           window_s: Optional[float] = None) -> str:
    """One table line per replica. QPS needs two scrapes (prev +
    window); single-shot runs show cumulative totals instead."""
    out = []
    hdr = (f"{'ENDPOINT':22} {'QPS':>7} {'SERVED':>8} {'SHED':>7} "
           f"{'DEADLN':>7} {'QDEPTH':>6} {'P50MS':>8} {'P99MS':>8} "
           f"{'TOK/S':>7} {'DEC/PRE':>11} {'KVRES':>6} {'PFXHIT':>6} "
           f"{'RESUME':>6} {'PREEMPT':>7} "
           f"{'TTFT50':>7} {'TTFT99':>7} {'TPOT50':>7} {'TPOT99':>7} "
           f"{'DEDUP':>5} "
           f"{'EPOCH':>6} {'DRAIN':>5}")
    out.append(hdr)
    for row in rows:
        ep = row["endpoint"]
        if "error" in row:
            out.append(f"{ep:22} DOWN: {row['error']}")
            continue
        s = row.get("serving", {})
        g = row.get("generation") or {}
        served = int(s.get("served_total", 0))
        qps = ""
        if prev is not None and window_s and ep in prev:
            prev_served = int(
                prev[ep].get("serving", {}).get("served_total", 0))
            qps = f"{(served - prev_served) / window_s:7.1f}"
        else:
            qps = f"{'-':>7}"
        shed = (int(s.get("shed_total", 0))
                + int(g.get("shed_total", 0)))
        ddl = (int(s.get("deadline_exceeded_total", 0))
               + int(g.get("deadline_exceeded_total", 0)))
        qdepth = (int(s.get("queue_depth", 0))
                  + int(g.get("queue_depth", 0)))
        gen_cols = _gen_columns(
            row, prev.get(ep) if prev is not None else None, window_s)
        out.append(
            f"{ep:22} {qps} {served:8d} "
            f"{shed:7d} "
            f"{ddl:7d} "
            f"{qdepth:6d} "
            f"{_fmt_ms(s.get('p50_ms'))} {_fmt_ms(s.get('p99_ms'))} "
            f"{gen_cols} "
            f"{int(s.get('weight_epoch', 0)):6d} "
            f"{'yes' if s.get('draining') else 'no':>5}")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="servetop", description=__doc__)
    p.add_argument("--endpoints", required=True,
                   help="comma-separated serving replica host:port list")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object (list of per-replica "
                        "stats) instead of the table")
    p.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                   help="re-scrape every SECS seconds (QPS computed "
                        "over the window); ctrl-C to stop")
    p.add_argument("--deadline", type=float, default=5.0,
                   help="per-replica scrape RPC deadline (seconds)")
    args = p.parse_args(argv)
    endpoints = [e.strip() for e in args.endpoints.split(",")
                 if e.strip()]
    if not endpoints:
        print("servetop: --endpoints is empty", file=sys.stderr)
        return 2

    rows = scrape(endpoints, deadline=args.deadline)
    if args.json and not args.watch:
        print(json.dumps(rows, default=str, indent=1))
        return 0
    print(render(rows))
    if not args.watch:
        return 0
    prev = {r["endpoint"]: r for r in rows}
    try:
        while True:
            time.sleep(args.watch)
            rows = scrape(endpoints, deadline=args.deadline)
            if args.json:
                print(json.dumps(rows, default=str))
            else:
                print(render(rows, prev=prev, window_s=args.watch))
            prev = {r["endpoint"]: r for r in rows}
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
