#!/usr/bin/env python
"""tracetop: merge per-process span dumps into causal traces and
attribute each sync round's critical path (ISSUE 9).

Input: a directory of `flightrec.<tag>.json` flight-recorder dumps
(written by telemetry/tracing.py — on SIGTERM/crash/exit per process,
or live via debugz /tracez). The launcher's --trace_dir leaves one per
trainer rank, per pserver, and one for the coordinator.

What it does:

  merge          all processes' spans, keyed by the wire-propagated
                 trace_id — one trainer step's trace spans trainer ->
                 primary -> backup -> coordinator. Process labels reuse
                 the timeline merger's pid scheme (telemetry/timeline.
                 process_pid_base) so Perfetto lanes and tracetop rows
                 name processes identically.
  sync rounds    every server-side push span carries (table, round,
                 trainer) attributes and the barrier releaser is marked
                 (released_round); per round tracetop reconstructs WHO
                 held the barrier (last arrival), for how long (arrival
                 spread), what each peer paid (barrier_wait), and where
                 the released round's time went (handle/apply/replicate
                 forwards) — per-round culprit attribution the
                 straggler detector can cite instead of inferring from
                 heartbeat medians.
  slowest traces a tracez-style listing across processes (--traces).

Usage:
  python tools/tracetop.py <trace_dir>              # per-round report
  python tools/tracetop.py <trace_dir> --json       # machine-readable
  python tools/tracetop.py <trace_dir> --traces 10  # slowest traces
  python tools/tracetop.py <trace_dir> --table emb  # filter by table
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

from paddle_tpu.telemetry.timeline import process_pid_base  # noqa: E402

# server-side verbs that participate in a sync/push round
_PUSH_SPANS = ("server:push_gradients", "server:push_delta")


def load_dumps(directory: str) -> List[dict]:
    """Every parseable flightrec.<tag>.json in `directory` (unreadable
    files are skipped with a warning — a torn dump from a crashing
    process must not cost the survivors' report)."""
    dumps = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "flightrec.*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[tracetop] skipping unreadable dump {path}: {e}",
                  file=sys.stderr)
            continue
        if isinstance(d, dict) and isinstance(d.get("spans"), list):
            dumps.append(d)
    return dumps


def merged_spans(dumps: List[dict]) -> List[dict]:
    """All spans across dumps, each stamped with its dump's process tag
    (the span's own `proc` wins when present)."""
    out = []
    for d in dumps:
        tag = d.get("process", "?")
        for s in d["spans"]:
            s = dict(s)
            s.setdefault("proc", tag)
            out.append(s)
    out.sort(key=lambda s: s.get("ts", 0.0))
    return out


def _index(spans: List[dict]):
    by_id: Dict[str, dict] = {}
    children: Dict[str, List[dict]] = {}
    for s in spans:
        if s.get("span"):
            by_id[s["span"]] = s
        if s.get("parent"):
            children.setdefault(s["parent"], []).append(s)
    return by_id, children


def _child(children, span, name) -> Optional[dict]:
    for c in children.get(span["span"], ()):
        if c["name"] == name:
            return c
    return None


def _client_hop(by_id, push_span) -> dict:
    """Walk the server push span back to the trainer's client spans:
    parent is the attempt span, whose parent is the rpc span — the
    client-side wall time (retries + backoff included) for this hop."""
    out = {"client_ms": None, "attempts": None, "backoff_ms": None}
    att = by_id.get(push_span.get("parent") or "")
    if att is None:
        return out
    rpc = by_id.get(att.get("parent") or "")
    if rpc is None:
        return out
    out["client_ms"] = rpc.get("dur_ms")
    sib = [s for s in by_id.values() if s.get("parent") == rpc["span"]]
    out["attempts"] = sum(1 for s in sib
                          if s["name"].startswith("attempt:"))
    out["backoff_ms"] = round(sum(s.get("dur_ms", 0.0) for s in sib
                                  if s["name"] == "backoff"), 3)
    return out


def sync_rounds(spans: List[dict],
                table: Optional[str] = None) -> List[dict]:
    """Group server-side push spans into rounds and reconstruct each
    round's critical path. Returns one dict per (table, round, serving
    process), sorted by (table, round)."""
    by_id, children = _index(spans)
    groups: Dict[tuple, List[dict]] = {}
    for s in spans:
        if s["name"] not in _PUSH_SPANS:
            continue
        attrs = s.get("attrs") or {}
        if "round" not in attrs:
            continue
        tbl = attrs.get("table", "?")
        if table is not None and tbl != table:
            continue
        groups.setdefault((str(tbl), int(attrs["round"]),
                           s.get("proc", "?")), []).append(s)
    rounds = []
    for (tbl, rnd, proc), pushes in sorted(groups.items()):
        pushes.sort(key=lambda s: s["ts"])
        t_first = pushes[0]["ts"]
        hops = []
        releaser = None
        for p in pushes:
            attrs = p.get("attrs") or {}
            wait = _child(children, p, "barrier_wait")
            apply_sp = _child(children, p, "apply")
            hop = {
                "trainer": attrs.get("trainer"),
                "verb": p["name"].split(":", 1)[1],
                "arrival_offset_ms": round((p["ts"] - t_first) * 1e3, 3),
                "handle_ms": p.get("dur_ms"),
                "wait_ms": (wait.get("dur_ms") if wait else 0.0),
                "apply_ms": (apply_sp.get("dur_ms") if apply_sp else None),
                "released": attrs.get("released_round") == rnd
                            or (attrs.get("released_round") is not None
                                and int(attrs["released_round"]) == rnd),
                "trace": p.get("trace"),
                "retry": bool(attrs.get("retry")),
            }
            hop.update(_client_hop(by_id, p))
            # replication forwards issued while applying this round
            fw = apply_sp or p
            hop["forwards"] = [
                {"peer": (c.get("attrs") or {}).get("peer"),
                 "ms": c.get("dur_ms")}
                for c in children.get(fw["span"], ())
                if c["name"] == "rpc:replicate"]
            hops.append(hop)
            if hop["released"]:
                releaser = hop
        if releaser is None:  # releaser mark missing: last arrival wins
            releaser = hops[-1]
        rounds.append({
            "table": tbl, "round": rnd, "server": proc,
            "world": len(hops), "hops": hops,
            "culprit": {
                "trainer": releaser["trainer"],
                "verb": releaser["verb"],
                "critical_ms": releaser["arrival_offset_ms"],
                "trace": releaser["trace"],
            },
            "peer_wait_ms": round(max((h["wait_ms"] or 0.0)
                                      for h in hops), 3),
        })
    return rounds


def slowest_traces(spans: List[dict], topk: int = 10) -> List[dict]:
    """Cross-process tracez: whole traces ranked by end-to-end span."""
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    out = []
    for tid, ss in by_trace.items():
        ss.sort(key=lambda s: s["ts"])
        t0 = min(s["ts"] for s in ss)
        t1 = max(s["ts"] + s.get("dur_ms", 0.0) / 1e3 for s in ss)
        ids = {s["span"] for s in ss}
        roots = [s for s in ss if not s.get("parent")
                 or s["parent"] not in ids]
        out.append({"trace": tid, "dur_ms": round((t1 - t0) * 1e3, 3),
                    "root": roots[0]["name"] if roots else ss[0]["name"],
                    "procs": sorted({s.get("proc", "?") for s in ss}),
                    "n_spans": len(ss), "spans": ss})
    out.sort(key=lambda t: -t["dur_ms"])
    return out[:topk]


def _label(proc) -> str:
    return process_pid_base(proc)[1]


def format_round(r: dict) -> str:
    c = r["culprit"]
    head = (f"round {r['round']:>4} table={r['table']} "
            f"server={_label(r['server'])}: barrier released by "
            f"trainer {c['trainer']} ({c['verb']}) "
            f"+{c['critical_ms']:.1f}ms after first arrival; "
            f"peers waited {r['peer_wait_ms']:.1f}ms "
            f"[trace {str(c['trace'])[:16]}]")
    lines = [head]
    for h in sorted(r["hops"], key=lambda h: h["arrival_offset_ms"]):
        extra = ""
        if h.get("client_ms") is not None:
            extra += f" client={h['client_ms']:.1f}ms"
            if h.get("attempts") and h["attempts"] > 1:
                extra += (f" ({h['attempts']} attempts,"
                          f" backoff {h['backoff_ms']:.1f}ms)")
        if h.get("apply_ms") is not None:
            extra += f" apply={h['apply_ms']:.1f}ms"
        for fw in h.get("forwards", ()):
            extra += f" replicate->{fw['peer']}={fw['ms']:.1f}ms"
        mark = "*" if h["released"] else " "
        lines.append(
            f"  {mark} trainer {h['trainer']}: "
            f"arrival +{h['arrival_offset_ms']:.1f}ms "
            f"wait={h['wait_ms'] or 0.0:.1f}ms "
            f"handle={h['handle_ms']:.1f}ms{extra}")
    return "\n".join(lines)


def format_trace(t: dict) -> str:
    head = (f"trace {t['trace'][:16]} root={t['root']} "
            f"{t['dur_ms']:.1f}ms over {t['n_spans']} spans "
            f"({', '.join(_label(p) for p in t['procs'])})")
    lines = [head]
    for s in t["spans"]:
        lines.append(f"    {_label(s.get('proc', '?')):>12} "
                     f"{s['name']:<28} {s.get('dur_ms', 0.0):9.2f}ms "
                     f"{s.get('status', 'ok')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tracetop",
        description="merge flight-recorder span dumps; attribute each "
                    "sync round's critical path")
    p.add_argument("trace_dir", help="directory of flightrec.<tag>.json "
                                     "dumps (launch.py --trace_dir)")
    p.add_argument("--table", default=None,
                   help="only rounds of this table")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--traces", type=int, default=0, metavar="K",
                   help="also list the K slowest whole traces")
    p.add_argument("--topk", type=int, default=0,
                   help="only the K worst rounds (by critical_ms)")
    args = p.parse_args(argv)

    dumps = load_dumps(args.trace_dir)
    if not dumps:
        print(f"[tracetop] no flightrec.*.json dumps in "
              f"{args.trace_dir!r} — run with PADDLE_TRACING=1 and "
              f"PADDLE_TRACE_DIR (launch.py --trace_dir arms both)",
              file=sys.stderr)
        return 1
    spans = merged_spans(dumps)
    rounds = sync_rounds(spans, table=args.table)
    if args.topk:
        rounds = sorted(rounds,
                        key=lambda r: -r["culprit"]["critical_ms"]
                        )[:args.topk]
    if args.json:
        out = {"processes": sorted({d.get("process", "?")
                                    for d in dumps}),
               "n_spans": len(spans), "rounds": rounds}
        if args.traces:
            out["slowest_traces"] = slowest_traces(spans, args.traces)
        json.dump(out, sys.stdout, default=str)
        print()
        return 0
    print(f"[tracetop] {len(dumps)} process dumps "
          f"({', '.join(sorted(_label(d.get('process', '?')) for d in dumps))}), "
          f"{len(spans)} spans, {len(rounds)} sync rounds")
    for r in rounds:
        print(format_round(r))
    if args.traces:
        print(f"\nslowest {args.traces} traces:")
        for t in slowest_traces(spans, args.traces):
            print(format_trace(t))
    return 0


if __name__ == "__main__":
    sys.exit(main())
