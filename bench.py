"""Benchmark: BERT-base pretraining step (fwd+bwd+Adam) tokens/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.35 (the BASELINE.json north star:
ERNIE/BERT-base pretraining at >=35% MFU; the reference publishes no
in-repo numbers — see BASELINE.md).

Measurement protocol (steady state, device-resident data):
  - bf16 AMP via the framework's own rewriter (contrib/mixed_precision),
    reference parity point decorator.py:218
  - the fixed batch is uploaded to the device ONCE; the step loop issues
    async dispatches and syncs once at the end — matching how a real
    input pipeline (device prefetch) behaves, and excluding the dev-type
    tunnel's host<->device latency from steady-state numbers
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _peak_flops_per_chip():
    """bf16 peak FLOP/s for the local chip. The detection table lives in
    telemetry/cost.py so the bench rows and the measured-MFU gauge share
    one denominator."""
    from paddle_tpu.telemetry.cost import peak_flops_per_chip

    return peak_flops_per_chip()


def _bert_step_flops(cfg, batch, seq):
    """fwd+bwd FLOPs per step: 6*N per token for matmul params (fwd 2N,
    bwd 4N) + attention scores/context 12*L*S*H per token."""
    h, L, ff, v = cfg.hidden_size, cfg.num_hidden_layers, cfg.intermediate_size, cfg.vocab_size
    # parameter FLOP-active matmuls: qkv+out (4 h^2) + ffn (2 h ff) per layer
    n_matmul = L * (4 * h * h + 2 * h * ff) + v * h  # + lm head / embedding tie
    per_token = 6 * n_matmul + 12 * L * seq * h
    return per_token * batch * seq


def _timed_run(exe, program, data, loss, steps):
    """Shared measurement protocol: 2-step compile warmup + sync, async
    step loop, one trailing sync; BENCH_PROFILE wraps the timed loop.
    Returns (dt_seconds, final_loss)."""
    import contextlib

    import numpy as np

    import paddle_tpu.fluid as fluid

    for _ in range(2):
        (lv,) = exe.run(program, feed=data, fetch_list=[loss])
    float(np.asarray(lv).reshape(()))

    profile_path = os.environ.get("BENCH_PROFILE", "")
    ctx = (
        fluid.profiler.profiler(state="All", profile_path=profile_path)
        if profile_path
        else contextlib.nullcontext()
    )
    with ctx:
        t0 = time.perf_counter()
        for _ in range(steps):
            (lv,) = exe.run(program, feed=data, fetch_list=[loss],
                            return_numpy=False)
        lv = float(np.asarray(lv).reshape(()))  # one sync at the end
        dt = time.perf_counter() - t0
    assert np.isfinite(lv), f"loss not finite: {lv}"
    return dt, lv


def _maybe_op_profile(exe, program, data, loss, formula_flops_per_step,
                      model):
    """BENCH_OP_PROFILE=1: after the timed loop, re-run a few steps
    under FLAGS_op_profile and report the measured-MFU gauge + per-op
    attribution coverage in the bench row (telemetry/cost.py; the full
    report lands on the debugz /proftop endpoint and in the registry).
    The full CostReport is also persisted beside the BENCH_*.json rows
    as bench_artifacts/proftop_<model>_rNN.json (NN = next free round),
    so per-op cost history accumulates across rounds for regression
    diffing. Off = empty dict, the timed loop untouched."""
    if os.environ.get("BENCH_OP_PROFILE", "0") != "1":
        return {}
    from paddle_tpu.telemetry import cost

    rep = cost.profile_executor_run(
        exe, program, data, [loss],
        steps=int(os.environ.get("BENCH_OP_PROFILE_STEPS", "3")),
        formula_flops_per_step=formula_flops_per_step, model=model)
    _persist_cost_report(rep, model)
    return {
        "measured_mfu": rep.measured_mfu,
        "op_profile_coverage": round(rep.coverage, 4),
    }


def _persist_cost_report(rep, model) -> None:
    """Write the CostReport to bench_artifacts/proftop_<model>_rNN.json
    (atomic; NN picks up where the existing history leaves off —
    `diff`-able per-op cost rows across bench rounds). BENCH_ARTIFACTS
    overrides the directory; failures never fail the bench."""
    import glob
    import re

    try:
        art_dir = os.environ.get("BENCH_ARTIFACTS") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_artifacts")
        os.makedirs(art_dir, exist_ok=True)
        taken = []
        for p in glob.glob(os.path.join(art_dir, f"proftop_{model}_r*.json")):
            m = re.search(r"_r(\d+)\.json$", p)
            if m:
                taken.append(int(m.group(1)))
        path = os.path.join(
            art_dir, f"proftop_{model}_r{max(taken, default=0) + 1:02d}.json")
        blob = json.dumps(rep.to_json(), indent=1)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(blob)
        os.replace(tmp, path)
        print(f"# proftop report persisted: {path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — history is best-effort
        print(f"# proftop report persist failed: {e}", file=sys.stderr)


def _autotune_fields():
    """BENCH_r06+ rows record the ACTIVE autotune cache hash and the
    per-kernel configs chosen while tracing (ISSUE 13), next to the
    peak_hbm_bytes/hbm_model_bytes fields — a perf row is reproducible
    only if it names the kernel configs that produced it. {} with
    FLAGS_kernel_autotune off (rows bit-identical to before);
    BENCH_KERNEL_AUTOTUNE=1 arms the flag for a bench run."""
    import paddle_tpu.fluid as fluid

    if not fluid.flags.get_flags(
            "FLAGS_kernel_autotune")["FLAGS_kernel_autotune"]:
        return {}
    from paddle_tpu import tuning

    return {
        "kernel_autotune": True,
        "autotune_cache_hash": tuning.cache_fingerprint(),
        "autotune_configs": tuning.chosen_configs(),
    }


def _goodput_snapshot():
    """Stash the goodput ledger's per-bucket totals before the timed
    loop; None when PADDLE_GOODPUT is off (the default — rows stay
    bit-identical to before)."""
    try:
        from paddle_tpu.telemetry import goodput

        led = goodput.get_ledger()
        if led is None:
            return None
        return dict(led.summary()["buckets_ms"])
    except Exception:  # noqa: BLE001 — diagnostics must not fail the bench
        return None


def _goodput_fields(before):
    """BENCH_r17+ rows join the goodput ledger (PADDLE_GOODPUT=1): the
    per-bucket badput DELTA accrued over the timed loop plus the
    job-lifetime goodput ratio, so a perf row names the stalls and
    preemptions it absorbed instead of averaging them away silently.
    {} when the ledger is off."""
    if before is None:
        return {}
    try:
        from paddle_tpu.telemetry import goodput

        led = goodput.get_ledger()
        if led is None:
            return {}
        summ = led.summary()
        after = summ["buckets_ms"]
        delta = {b: round(after.get(b, 0.0) - before.get(b, 0.0), 3)
                 for b in after
                 if after.get(b, 0.0) - before.get(b, 0.0) > 1e-9}
        return {"goodput_delta_ms": delta,
                "goodput_ratio": summ.get("goodput_ratio")}
    except Exception:  # noqa: BLE001
        return {}


def _memory_fields(exe, program, data, loss, hbm_model_bytes=None):
    """BENCH_r06+ rows record memory alongside MFU (ISSUE 11):
    `peak_hbm_bytes` — XLA's buffer-assignment peak for the compiled
    step (measured bytes, the raw form of the existing peak_hbm_gb) —
    and `hbm_model_bytes` — params + optimizer state from the static
    live-range attribution (telemetry/memory.py), i.e. the resident
    floor a bigger batch cannot shrink. Best-effort: {} on backends
    that cannot report."""
    out = {}
    try:
        ma = exe.memory_analysis(program, feed=data, fetch_list=[loss])
        out["peak_hbm_bytes"] = int(ma["peak_bytes"])
    except Exception:  # noqa: BLE001 — diagnostics must not fail the bench
        pass
    try:
        if hbm_model_bytes is None:
            from paddle_tpu.telemetry import memory as _mem

            rep = _mem.build_memory_report(
                program, feed_shapes=data, fetch_names=[loss.name],
                publish=False)
            hbm_model_bytes = rep.static.model_bytes
        out["hbm_model_bytes"] = int(hbm_model_bytes)
    except Exception:  # noqa: BLE001
        pass
    return out


def _emit_result(result: dict) -> None:
    """Print THE one JSON result line (the bench contract) and publish
    the same row through the unified telemetry layer — a gauge per
    numeric field in the process registry plus a kind="bench" JSONL
    record when PADDLE_METRICS_PATH is set — so BENCH_* numbers and
    production telemetry share one code path (ISSUE 4)."""
    print(json.dumps(result))
    from paddle_tpu import telemetry

    reg = telemetry.get_registry()
    metric = str(result.get("metric", "bench"))
    for key in ("value", "mfu", "peak_hbm_gb", "peak_hbm_bytes",
                "hbm_model_bytes", "vs_baseline"):
        v = result.get(key)
        if isinstance(v, (int, float)):
            reg.gauge(f"bench_{key}", metric=metric).set(v)
    telemetry.emit({"kind": "bench", **result})


def bench_resnet(depth=50):
    """Secondary tracked configs (BASELINE.md): ResNet images/sec/chip,
    any depth in the hapi roster (BENCH_MODEL=resnet18/34/50/101/152).
    BASELINE.md sets no ResNet target ("TBD"), so vs_baseline reports
    raw MFU rather than a ratio against an invented bar.

    BENCH_CONV_BN_FUSION=1 routes every conv->BN(->relu) triple through
    the fused_conv_bn mega-kernel (fluid/fusion_pass.py +
    ops/pallas/conv_bn.py); default 0 keeps the tracked baseline
    schedule. The fusion flag is reported in the JSON row."""
    import jax
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib import mixed_precision as mixed_prec
    from paddle_tpu.models.resnet import (
        ResNetConfig,
        build_resnet_train_program,
        resnet_step_flops,
    )

    cfg = getattr(ResNetConfig, f"resnet{depth}")()
    batch = int(os.environ.get("BENCH_BATCH", 128))
    size = int(os.environ.get("BENCH_IMAGE", 224))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    use_amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_fusion = os.environ.get("BENCH_CONV_BN_FUSION", "0") == "1"
    fluid.flags.set_flags({"FLAGS_conv_bn_fusion": use_fusion})

    main_p, startup = fluid.Program(), fluid.Program()
    m, st, feeds, loss = build_resnet_train_program(cfg, batch, size, main_p, startup)
    with fluid.program_guard(m, st):
        opt = fluid.optimizer.MomentumOptimizer(learning_rate=0.1, momentum=0.9)
        if use_amp:
            opt = mixed_prec.decorate(opt, use_bf16=True)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(st)
    rng = np.random.RandomState(0)
    data = {
        "image": jax.device_put(rng.rand(batch, 3, size, size).astype(np.float32)),
        "label": jax.device_put(rng.randint(0, 1000, (batch, 1)).astype(np.int64)),
    }
    gp0 = _goodput_snapshot()
    dt, _ = _timed_run(exe, m, data, loss, steps)
    imgs_per_sec = batch * steps / dt
    formula_flops = resnet_step_flops(cfg, batch, size)
    mfu = formula_flops * steps / dt / _peak_flops_per_chip()
    _emit_result({
        "metric": f"resnet{depth}_train_images_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "images/s/chip",
        "vs_baseline": None,  # BASELINE.md sets no ResNet target ("TBD")
        "mfu": round(mfu, 4),
        "batch": batch,
        "image_size": size,
        "steps": steps,
        "amp_bf16": use_amp,
        "conv_bn_fusion": use_fusion,
        **_memory_fields(exe, m, data, loss),
        **_autotune_fields(),
        **_goodput_fields(gp0),
        **_maybe_op_profile(exe, m, data, loss, formula_flops,
                            f"resnet{depth}"),
    })


def bench_transformer():
    """Transformer-base NMT WMT14 (the BASELINE.md configs-to-measure
    row; dist_transformer.py recipe) tokens/sec/chip. BASELINE.md's
    metric table sets no Transformer target, so vs_baseline is null and
    achieved utilization is reported in the separate "mfu" key."""
    import jax
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib import mixed_precision as mixed_prec
    from paddle_tpu.models.transformer import (
        TransformerConfig,
        build_transformer_nmt_program,
        random_nmt_batch,
        transformer_step_flops,
    )

    cfg = TransformerConfig.base()
    # at d_model 512 / s256 XLA's per-layer lowering beats the layer scan
    # (the stacked-param dynamic-slices dominate); the scan stays available
    # for deep/compile-bound configs via BENCH_FUSE=1
    cfg.fuse_stack = os.environ.get("BENCH_FUSE", "0") == "1"
    cfg.use_flash = os.environ.get("BENCH_FLASH", "1") == "1"
    # the non-fused path gates flash through the flag, not cfg
    fluid.flags.set_flags({"FLAGS_use_flash_attention": cfg.use_flash})
    batch = int(os.environ.get("BENCH_BATCH", 64))
    src_len = int(os.environ.get("BENCH_SRC", 256))
    trg_len = int(os.environ.get("BENCH_TRG", 256))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    use_amp = os.environ.get("BENCH_AMP", "1") == "1"

    m, st, feeds, loss = build_transformer_nmt_program(
        cfg, batch, src_len, trg_len)
    with fluid.program_guard(m, st):
        opt = fluid.optimizer.AdamOptimizer(learning_rate=1e-4)
        if use_amp:
            opt = mixed_prec.decorate(opt, use_bf16=True)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(st)
    data = {k: jax.device_put(np.asarray(v))
            for k, v in random_nmt_batch(cfg, batch, src_len, trg_len).items()}
    gp0 = _goodput_snapshot()
    dt, _ = _timed_run(exe, m, data, loss, steps)
    tokens_per_sec = batch * (src_len + trg_len) * steps / dt
    mfu = (transformer_step_flops(cfg, batch, src_len, trg_len) * steps / dt
           / _peak_flops_per_chip())
    _emit_result({
        "metric": "transformer_base_nmt_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": None,  # BASELINE.md sets no Transformer target
        "mfu": round(mfu, 4),
        "batch": batch,
        "src_len": src_len,
        "trg_len": trg_len,
        "steps": steps,
        "amp_bf16": use_amp,
        **_goodput_fields(gp0),
    })


# auto-remat escalation ladder: cheapest recompute first. The bench
# probes each candidate's XLA memory analysis (compile only, no execute)
# and runs the first whose projected peak fits HBM — no hand-picked
# BENCH_REMAT_* env vars needed for long-context configs. Measured on
# v5e s512/b64 (BSH kernel): remat_ffn 0.572 MFU @ 10.2G, policy
# 'flash' 0.545 @ 4.6G, remat_layer last resort.
_REMAT_LADDER = (
    {"remat_ffn": True},
    {"remat_policy": "flash"},
    {"remat_layer": True},
)


def _remat_from_env():
    """Explicit BENCH_REMAT_* env vars override the auto ladder."""
    out = {}
    for env, field in (
        ("BENCH_REMAT_FFN", "remat_ffn"),
        ("BENCH_REMAT_QKV", "remat_qkv"),
        ("BENCH_REMAT_LAYER", "remat_layer"),
    ):
        if env in os.environ:
            out[field] = os.environ[env] == "1"
    if os.environ.get("BENCH_REMAT_POLICY"):
        out["remat_policy"] = os.environ["BENCH_REMAT_POLICY"]
    return out or None


def _hbm_limit_bytes():
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — CPU/interpret backends
        pass
    return None


def _apply_smoke_defaults():
    """`bench.py --smoke` (CI): tiny shapes, 2 steps — asserts the bench
    path still builds, trains, and emits one valid JSON line on the CPU
    backend. Explicit BENCH_* env vars still win (setdefault)."""
    for k, v in (
        ("BENCH_BATCH", "2"),
        ("BENCH_STEPS", "2"),
        ("BENCH_IMAGE", "32"),
        ("BENCH_SEQ", "64"),
        ("BENCH_SRC", "32"),
        ("BENCH_TRG", "32"),
        ("BENCH_LONG_SEQ", "0"),
    ):
        os.environ.setdefault(k, v)


def main():
    if "--smoke" in sys.argv:
        _apply_smoke_defaults()
    if os.environ.get("BENCH_KERNEL_AUTOTUNE", "0") == "1":
        # route the Pallas kernels through the per-chip tuning cache
        # (the BENCH_r06 protocol knob; bench_artifacts/autotune.md)
        import paddle_tpu.fluid as fluid

        fluid.flags.set_flags({"FLAGS_kernel_autotune": True})
    model = os.environ.get("BENCH_MODEL", "bert")
    if model.startswith("resnet"):
        return bench_resnet(int(model[len("resnet"):] or 50))
    if model == "transformer":
        return bench_transformer()

    batch = int(os.environ.get("BENCH_BATCH", 64))
    seq = int(os.environ.get("BENCH_SEQ", 512))
    # 76 is the tracked-config value (s512); clamp for short --smoke
    # sequences — more masked predictions than tokens cannot gather
    max_preds = min(76, seq // 2)
    steps = int(os.environ.get("BENCH_STEPS", 30))
    use_amp = os.environ.get("BENCH_AMP", "1") == "1"

    out = _run_bert(batch, seq, max_preds, steps, use_amp)
    result = {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": out["tokens_per_sec"],
        "unit": "tokens/s/chip",
        "vs_baseline": round(out["mfu"] / 0.35, 4),
        "mfu": out["mfu"],
        "batch": batch,
        "seq_len": seq,
        "steps": steps,
        "amp_bf16": use_amp,
        "remat": out["remat"],
        "peak_hbm_gb": out["peak_hbm_gb"],
    }
    for k in ("measured_mfu", "op_profile_coverage", "peak_hbm_bytes",
              "hbm_model_bytes", "kernel_autotune", "autotune_cache_hash",
              "autotune_configs"):
        if k in out:
            result[k] = out[k]
    # long-context guard row (VERDICT r3: the s4096 config regressed with
    # nothing measuring it): the default bench also runs s4096/b8 through
    # the auto-remat ladder and reports it in the same JSON line
    if seq == 512 and os.environ.get("BENCH_LONG_SEQ", "1") == "1":
        # full step count: at 15 steps the s4096 row reads ~0.5 MFU-pt
        # low on the shared chip (±5% noise, env-gotchas); the row
        # exists to catch regressions, so measure it as carefully as
        # the main row
        ls = _run_bert(8, 4096, max_preds, steps, use_amp)
        result["long_seq"] = {
            "seq_len": 4096, "batch": 8, "mfu": ls["mfu"],
            "tokens_per_sec": ls["tokens_per_sec"], "remat": ls["remat"],
            "vs_long_target": round(ls["mfu"] / 0.37, 4),
        }
    print(json.dumps(result))


def _run_bert(batch, seq, max_preds, steps, use_amp):
    """Build + auto-remat-select + measure one BERT pretraining config."""
    import dataclasses

    import jax
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.contrib import mixed_precision as mixed_prec
    from paddle_tpu.models.bert import (
        BertConfig,
        build_bert_pretrain_program,
        random_pretrain_batch,
    )

    base_cfg = BertConfig.base()
    base_cfg.fuse_stack = True  # scan over layers: O(1)-in-depth compile time
    # long-context runs: the position table must cover the sequence
    base_cfg.max_position_embeddings = max(base_cfg.max_position_embeddings, seq)

    def build(remat):
        cfg = dataclasses.replace(base_cfg, **remat)
        main_p, startup = fluid.Program(), fluid.Program()
        m, st, _feeds, loss = build_bert_pretrain_program(
            cfg, batch, seq, max_preds, main_program=main_p,
            startup_program=startup,
        )
        with fluid.program_guard(m, st):
            opt = fluid.optimizer.AdamOptimizer(learning_rate=1e-4)
            if use_amp:
                opt = mixed_prec.decorate(opt, use_bf16=True)
            opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(st)
        return cfg, exe, m, loss

    data = random_pretrain_batch(base_cfg, batch, seq, max_preds, seed=0)
    # device-resident feed: upload once, reuse every step
    data = {k: jax.device_put(np.asarray(v)) for k, v in data.items()}

    env_remat = _remat_from_env()
    candidates = [env_remat] if env_remat else list(_REMAT_LADDER)
    limit = _hbm_limit_bytes()
    peak_gb = None
    for i, remat in enumerate(candidates):
        cfg, exe, m, loss = build(remat)
        last = i == len(candidates) - 1
        if last and limit is None:
            break
        try:
            ma = exe.memory_analysis(m, feed=data, fetch_list=[loss])
        except Exception as e:  # XLA compile-time HBM OOM -> escalate
            if last or "memory" not in str(e).lower():
                raise
            print(f"# remat {remat} failed to compile: OOM; escalating",
                  file=sys.stderr)
            continue
        peak_gb = round(ma["peak_bytes"] / 2**30, 3)
        if last or limit is None or ma["peak_bytes"] <= limit * 0.95:
            break
        print(f"# remat {remat} projected {peak_gb} GiB > "
              f"{round(0.95 * limit / 2**30, 2)} GiB budget; escalating",
              file=sys.stderr)

    gp0 = _goodput_snapshot()
    dt, _ = _timed_run(exe, m, data, loss, steps)
    formula_flops = _bert_step_flops(cfg, batch, seq)
    mfu = formula_flops * steps / dt / _peak_flops_per_chip()
    remat_desc = cfg.remat_policy or ",".join(
        k for k in ("remat_ffn", "remat_qkv", "remat_layer")
        if getattr(cfg, k)
    ) or "none"
    mem_fields = _memory_fields(exe, m, data, loss)
    if peak_gb is not None and "peak_hbm_bytes" not in mem_fields:
        mem_fields["peak_hbm_bytes"] = int(peak_gb * 2**30)
    return {
        "tokens_per_sec": round(batch * seq * steps / dt, 1),
        "mfu": round(mfu, 4),
        "remat": remat_desc,
        "peak_hbm_gb": peak_gb if peak_gb is not None
        else _peak_hbm_gb(exe, m, data, loss),
        **mem_fields,
        **_autotune_fields(),
        **_goodput_fields(gp0),
        **_maybe_op_profile(exe, m, data, loss, formula_flops, "bert"),
    }


def _peak_hbm_gb(exe, program, data, loss):
    """XLA's buffer-assignment peak for the compiled step (the measured
    form of the remat-vs-batch tradeoff); None when the backend cannot
    report it."""
    try:
        ma = exe.memory_analysis(program, feed=data, fetch_list=[loss])
        return round(ma["peak_bytes"] / 2**30, 3)
    except Exception:  # noqa: BLE001 — diagnostics must not fail the bench
        return None


if __name__ == "__main__":
    main()
