"""Training numerics observability (ISSUE 12): the in-graph tensor-stat
layer, the NaN-provenance doctor, AMP loss-scale telemetry + the
unified bad-step guard, gradient-clip observability, cross-replica SDC
detection, /numericz, and the numtop CLI.

Layers under test:
  ops/misc_ops.py                  the tensor_stats reduction emitter
  telemetry/numerics.py            watch install, sampling, history,
                                   doctor bisection, fingerprints,
                                   FingerprintTable, SDCReporter
  fluid/optimizer.py + clip.py     FLAGS_tensor_stats build hooks
  fluid/executor.py                cache key, step hook, doctor call
  contrib/mixed_precision          scale growth/backoff events, the
                                   where()-select overflow-skip fix,
                                   the backoff-exhausted guard
  distributed/faults.py            bitflip:<phase>:<nth> rule
  distributed/coordinator.py       numerics_report/status verbs + the
                                   eviction routing
  tools/numtop.py                  CLI end to end

The 2-process bitflip drill (ISSUE 12 acceptance: bitflip on 1 of 2 dp
ranks is detected, the divergence event names the corrupted rank within
K steps, all ranks flight-dump, the rank is evicted) runs in the slow
lane (tools/ci.sh numerics drill).
"""
import importlib.util
import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.contrib import mixed_precision as mp
from paddle_tpu.distributed import faults
from paddle_tpu.distributed.coordinator import (
    Coordinator, serve_coordinator, stop_coordinator,
)
from paddle_tpu.fluid import layers, monitor
from paddle_tpu.fluid import flags as fl
from paddle_tpu.fluid.checkpoint import BadStepError
from paddle_tpu.telemetry import debugz, get_registry, numerics, sink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_sdc_worker.py")


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _numerics_off():
    yield
    fl.set_flags({"FLAGS_tensor_stats": False,
                  "FLAGS_check_numerics": False,
                  "FLAGS_check_numerics_amp_scale_floor": 1.0})
    numerics._reset_for_tests()
    monitor.reset_for_tests()
    faults.reset()
    sink.disable()


def _linear_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        p = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(p, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(8, 4).astype(np.float32),
            rng.randn(8, 1).astype(np.float32))


def _train(main, startup, loss, feeds, scope=None):
    exe = fluid.Executor()
    scope = scope or fluid.executor.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = [float(np.asarray(
            exe.run(main, feed=f, fetch_list=[loss])[0]).reshape(-1)[0])
            for f in feeds]
    return out, scope


# ---------------------------------------------------------------------------
# tensor_stats op
# ---------------------------------------------------------------------------


def test_tensor_stats_emitter_matches_numpy():
    from paddle_tpu.ops import registry as ops_registry

    x = np.array([[1.0, -3.0, np.nan], [np.inf, 0.5, -np.inf]],
                 np.float32)
    ctx = ops_registry.EmitContext()
    out = np.asarray(ops_registry.get("tensor_stats").emit(
        ctx, {"X": [x]}, {})["Out"][0])
    assert out.shape == (4,) and out.dtype == np.float32
    nan_ct, inf_ct, max_abs, l2 = out
    assert nan_ct == 1 and inf_ct == 2
    # max/l2 over the FINITE elements only
    assert max_abs == pytest.approx(3.0)
    assert l2 == pytest.approx(np.sqrt(1 + 9 + 0.25), rel=1e-6)


# ---------------------------------------------------------------------------
# flag-off bit-identity + flag-on parity (the established contract)
# ---------------------------------------------------------------------------


def test_flag_off_builds_no_stat_ops_or_vars():
    main, _, _ = _linear_program()
    assert not [v.name for v in main.list_vars()
                if v.name.startswith(numerics.STAT_PREFIX)]
    assert not [op for op in main.global_block().ops
                if op.type == "tensor_stats"]
    assert getattr(main, "_numerics_watch", None) is None


def test_flag_on_watches_and_loss_trace_bit_identical():
    """The stat reductions are pure readers: the flag-on loss trace is
    BIT-identical to the flag-off one, and toggling the flag is in the
    compile-cache key."""
    xb, yb = _data()
    feeds = [{"x": xb, "y": yb}] * 4
    main_off, st_off, loss_off = _linear_program()
    trace_off, _ = _train(main_off, st_off, loss_off, feeds)

    fl.set_flags({"FLAGS_tensor_stats": True})
    main_on, st_on, loss_on = _linear_program()
    watches = getattr(main_on, "_numerics_watch", None)
    assert watches, "flag-on build must register watches"
    kinds = {m["kind"] for m in watches.values()}
    assert {"grad", "param"} <= kinds
    # one grad + one param watch per parameter
    n_params = len(main_on.all_parameters())
    assert len([m for m in watches.values()
                if m["kind"] == "grad"]) == n_params
    trace_on, _ = _train(main_on, st_on, loss_on, feeds)
    assert trace_on == trace_off


def test_step_record_schema_unchanged_by_flag(tmp_path):
    """kind="step" records keep their exact schema with the flag on;
    the numerics series rides its own kind="numerics" records."""
    path = str(tmp_path / "m.jsonl")
    sink.enable(path)
    fl.set_flags({"FLAGS_tensor_stats": True})
    main, startup, loss = _linear_program()
    xb, yb = _data()
    _train(main, startup, loss, [{"x": xb, "y": yb}] * 3)
    sink.disable()
    recs = [json.loads(l) for l in open(path)]
    steps = [r for r in recs if r["kind"] == "step"]
    assert steps
    need = {"kind", "step", "data_wait_ms", "compile_ms", "device_ms",
            "fetch_ms", "ckpt_save_ms", "idle_ms", "cache_hit", "fenced",
            "retraces", "peak_hbm_bytes", "ts", "rank"}
    for r in steps:
        assert need == set(r), f"step schema drifted: {sorted(r)}"
    nums = [r for r in recs if r["kind"] == "numerics"]
    assert nums, "flag-on armed run must emit numerics records"


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


def test_stats_sampled_every_n_steps(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_NUMERICS_EVERY", "2")
    path = str(tmp_path / "m.jsonl")
    sink.enable(path)
    fl.set_flags({"FLAGS_tensor_stats": True})
    main, startup, loss = _linear_program()
    xb, yb = _data()
    _train(main, startup, loss, [{"x": xb, "y": yb}] * 6)
    sink.disable()
    recs = [json.loads(l) for l in open(path)
            if json.loads(l)["kind"] == "numerics"]
    stats = [r for r in recs if r["event"] == "stats"]
    assert len(stats) == 3  # 6 steps / every 2
    watch = stats[-1]["watch"]
    grads = {k: v for k, v in watch.items() if v["kind"] == "grad"}
    assert grads and all(
        v["nan"] == 0 and v["inf"] == 0 and v["l2"] >= 0
        for v in grads.values())
    # history ring + gauges agree
    assert numerics.history()
    assert get_registry().gauge("numerics_grad_l2_total").value >= 0


def test_sampled_stats_overhead_bound():
    """The stat layer must stay cheap: fused in-graph reductions + one
    sampled host read. Median per-step wall time with the flag armed is
    bounded at 5x the flag-off median (generous: CI noise dominates at
    this model size; the point is catching an accidental per-step
    device sync or per-op host work)."""
    xb, yb = _data()
    feeds = [{"x": xb, "y": yb}] * 24

    def run(flag):
        fl.set_flags({"FLAGS_tensor_stats": flag})
        main, startup, loss = _linear_program()
        exe = fluid.Executor()
        scope = fluid.executor.Scope()
        times = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for i, f in enumerate(feeds):
                t0 = time.perf_counter()
                exe.run(main, feed=f, fetch_list=[loss])
                if i >= 4:  # skip compile + warmup
                    times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    base = run(False)
    armed = run(True)
    assert armed <= base * 5 + 2e-3, (armed, base)


# ---------------------------------------------------------------------------
# NaN-provenance doctor
# ---------------------------------------------------------------------------


def _overflow_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        from paddle_tpu.fluid.analysis import user_frame

        h = layers.scale(x, scale=1e30)
        h = layers.elementwise_mul(h, h)  # -> Inf HERE (first producer)
        bad_line = user_frame(h.op.attrs["__op_callstack__"])[1]
        p = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(p, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    ops = list(main.global_block().ops)
    mul_idx = [i for i, op in enumerate(ops)
               if op.type == "elementwise_mul"][0]
    return main, startup, loss, mul_idx, bad_line


def test_doctor_attributes_exact_op_and_callstack(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    fl.set_flags({"FLAGS_check_numerics": True,
                  "FLAGS_tensor_stats": True})
    main, startup, loss, mul_idx, bad_line = _overflow_program()
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(BadStepError) as ei:
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
    e = ei.value
    r = e.report
    # the exact IR op + the user layer call that built it
    assert r["provenance"] == "op"
    assert r["op_index"] == mul_idx
    assert r["op_type"] == "elementwise_mul"
    assert r["output_stats"]["inf"] > 0
    uf = r["user_frame"]
    assert uf and uf[0] == os.path.abspath(__file__) and uf[1] == bad_line
    assert any(op["stats"]["inf"] == 0 and op["stats"]["nan"] == 0
               for op in r["operands"]), "operands were finite"
    assert "first non-finite producer" in str(e)
    # the numrec flight-record landed and parses
    assert e.dump_path and os.path.exists(e.dump_path)
    dumped = json.load(open(e.dump_path))
    assert dumped["op_index"] == mul_idx
    assert dumped["kind"] == "numrec"
    assert os.path.basename(e.dump_path).startswith("numrec.")


def test_doctor_names_poisoned_input(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    fl.set_flags({"FLAGS_check_numerics": True})
    main, startup, loss = _linear_program()
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    bad = xb.copy()
    bad[0, 0] = np.nan
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        with pytest.raises(BadStepError) as ei:
            exe.run(main, feed={"x": bad, "y": yb}, fetch_list=[loss])
    assert ei.value.report["provenance"] == "input"
    assert ei.value.report["var"] == "x"


def test_doctor_opt_out(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_NUMERICS_DOCTOR", "0")
    fl.set_flags({"FLAGS_check_numerics": True})
    main, startup, loss, _, _ = _overflow_program()
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(BadStepError) as ei:
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
    assert not ei.value.report and ei.value.dump_path is None
    assert not [f for f in os.listdir(tmp_path)
                if f.startswith("numrec")]


def test_doctor_grad_history_rides_report(tmp_path, monkeypatch):
    """The sampled per-layer grad-norm series leading INTO the bad step
    is part of the numrec evidence."""
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    fl.set_flags({"FLAGS_tensor_stats": True,
                  "FLAGS_check_numerics": True})
    main, startup, loss = _linear_program()
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    bad = xb.copy()
    bad[0, 0] = np.inf
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        with pytest.raises(BadStepError) as ei:
            exe.run(main, feed={"x": bad, "y": yb}, fetch_list=[loss])
    hist = ei.value.report["grad_history"]
    assert len(hist) == 3
    assert all(h["event"] == "stats" for h in hist)


# ---------------------------------------------------------------------------
# AMP: scale telemetry, overflow-skip fix, unified guard
# ---------------------------------------------------------------------------


def _amp_program(init=4.0, incr_every=1000, decr_every=1,
                 decr_ratio=0.5):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        p = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(p, y))
        opt = mp.decorate(
            fluid.optimizer.SGDOptimizer(learning_rate=0.01),
            use_bf16=False, init_loss_scaling=init,
            incr_every_n_steps=incr_every,
            decr_every_n_nan_or_inf=decr_every, decr_ratio=decr_ratio)
        opt.minimize(loss)
    return main, startup, loss


_BIG = (np.ones((8, 4)) * 1e20).astype(np.float32)  # Inf after fp16 cast


def test_amp_scale_growth_and_backoff_events(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink.enable(path)
    get_registry().reset()
    main, startup, loss = _amp_program(init=4.0, incr_every=2)
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(4):  # 2 growths at incr_every=2
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        exe.run(main, feed={"x": _BIG, "y": yb}, fetch_list=[loss])
    sink.disable()
    reg = get_registry()
    assert reg.counter("numerics_amp_scale_growths_total").value == 2
    assert reg.counter("numerics_amp_scale_backoffs_total").value == 1
    assert reg.gauge("numerics_amp_loss_scale").value == 8.0
    recs = [json.loads(l) for l in open(path)
            if '"amp_scale"' in l]
    assert [r["change"] for r in recs] == ["growth", "growth",
                                           "backoff"]
    # events carry step numbers and the concrete scale transition
    assert all(isinstance(r["step"], int) and r["old"] != r["new"]
               for r in recs)


def test_amp_overflow_step_skips_without_poisoning_params():
    """Regression for the where()-select fix: the old keep-multiply
    zeroing computed inf * 0 = NaN, so the overflow step it meant to
    SKIP poisoned the parameters instead."""
    main, startup, loss = _amp_program(init=4.0)
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        before = {p.name: np.asarray(scope.find_var(p.name)).copy()
                  for p in main.all_parameters()}
        exe.run(main, feed={"x": _BIG, "y": yb}, fetch_list=[loss])
        for n, v in before.items():
            got = np.asarray(scope.find_var(n))
            assert np.isfinite(got).all(), f"{n} poisoned"
            np.testing.assert_array_equal(got, v)  # skipped = unchanged
        out = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        assert np.isfinite(out[0]).all()


def test_amp_transient_overflow_keeps_skip_semantics_under_guard():
    """FLAGS_check_numerics + AMP: a transient overflow (scale still
    above the floor) must NOT raise — AMP's zero-and-shrink skip owns
    it; the fp32 guard sees the zeroed (finite) grads."""
    fl.set_flags({"FLAGS_check_numerics": True})
    main, startup, loss = _amp_program(init=1024.0)
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        exe.run(main, feed={"x": _BIG, "y": yb}, fetch_list=[loss])
        exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])


def test_amp_backoff_exhausted_trips_unified_guard(tmp_path,
                                                   monkeypatch):
    """ISSUE 12 satellite: an AMP overflow that pushes the scale below
    the floor (backoff exhausted) raises BadStepError THROUGH the same
    doctor path as the fp32 guard — numrec dump included."""
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    fl.set_flags({"FLAGS_check_numerics": True})
    main, startup, loss = _amp_program(init=1.5, decr_ratio=0.5)
    assert [v.name for v in main.list_vars()
            if v.name.startswith("check_numerics_bad_amp")]
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    _, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(BadStepError) as ei:
            for _ in range(4):
                exe.run(main, feed={"x": _BIG, "y": yb},
                        fetch_list=[loss])
    assert "backoff exhausted" in str(ei.value)
    assert ei.value.dump_path and os.path.exists(ei.value.dump_path)
    assert ei.value.report.get("provenance") == "op"


def test_amp_guard_flag_off_builds_nothing():
    main, _, _ = _amp_program()
    assert not [v.name for v in main.list_vars()
                if v.name.startswith("check_numerics_bad")]


# ---------------------------------------------------------------------------
# gradient-clip observability
# ---------------------------------------------------------------------------


def test_clip_global_norm_gauge_and_trigger_counter(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink.enable(path)
    get_registry().reset()
    fl.set_flags({"FLAGS_tensor_stats": True})
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        p = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(p, y))
        clip = fluid.clip.GradientClipByGlobalNorm(clip_norm=1e-3)
        fluid.optimizer.SGDOptimizer(
            learning_rate=0.1, grad_clip=clip).minimize(loss)
    watches = getattr(main, "_numerics_watch", {})
    gn = [m for m in watches.values() if m["kind"] == "clip_gnorm"]
    assert len(gn) == 1 and gn[0]["clip_norm"] == pytest.approx(1e-3)
    xb, yb = _data()
    _train(main, startup, loss, [{"x": xb, "y": yb}] * 2)
    sink.disable()
    reg = get_registry()
    # a random-init regression's global grad norm dwarfs 1e-3: the
    # gauge carries the real norm and the trigger counter fired
    assert reg.gauge("grad_global_norm").value > 1e-3
    assert reg.counter("numerics_clip_triggered_total").value == 2
    recs = [json.loads(l) for l in open(path)
            if '"numerics"' in l]
    rows = [row for r in recs if r.get("event") == "stats"
            for row in r["watch"].values()
            if row["kind"] == "clip_gnorm"]
    assert rows and all(row["clipped"] for row in rows)


def test_clip_flag_off_discards_norm_as_before():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8, 4], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
        clip = fluid.clip.GradientClipByGlobalNorm(clip_norm=1.0)
        fluid.optimizer.SGDOptimizer(
            learning_rate=0.1, grad_clip=clip).minimize(loss)
    assert not [v.name for v in main.list_vars()
                if v.name.startswith(numerics.STAT_PREFIX)]


# ---------------------------------------------------------------------------
# SDC: fingerprints + detector + bitflip rule
# ---------------------------------------------------------------------------


def test_fingerprint_determinism_and_bit_sensitivity():
    a = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
         "m": np.ones(5, np.float32)}
    f1 = numerics.fingerprint_arrays(a)
    f2 = numerics.fingerprint_arrays({k: v.copy() for k, v in a.items()})
    assert f1 == f2
    b = {k: v.copy() for k, v in a.items()}
    b["w"].reshape(-1).view(np.uint32)[3] ^= 1  # one low bit
    f3 = numerics.fingerprint_arrays(b)
    assert f3["crc"] != f1["crc"]
    assert f1["norm"] == pytest.approx(
        float(np.sqrt(np.square(np.arange(12)).sum() + 5)))


def test_fingerprint_table_majority_names_odd_rank_out():
    t = numerics.FingerprintTable()
    good = {"crc": 111, "norm": 1.0}
    t.record(4, "trainer0", good, world_size=3)
    t.record(4, "trainer1", {"crc": 999, "norm": 5.0}, world_size=3)
    out = t.record(4, "trainer2", good, world_size=3)
    ev = out["event"]
    assert out["diverged"] and ev["odd_rank_out"] == ["trainer1"]
    assert ev["method"] == "majority" and ev["step"] == 4


def test_fingerprint_table_two_rank_tie_uses_self_consistency():
    t = numerics.FingerprintTable()
    t.record(2, "trainer0", {"crc": 1, "norm": 1.0,
                             "consistent": True}, world_size=2)
    out = t.record(2, "trainer1", {"crc": 2, "norm": 9.0,
                                   "consistent": False}, world_size=2)
    ev = out["event"]
    assert ev["odd_rank_out"] == ["trainer1"]
    assert ev["method"] == "self_check"


def test_fingerprint_table_agreement_and_latching():
    t = numerics.FingerprintTable()
    fp = {"crc": 7, "norm": 1.0}
    assert not t.record(2, "a", fp, 2)["diverged"]
    assert not t.record(2, "b", fp, 2)["diverged"]
    assert t.status()["events"] == []
    t.record(4, "a", {"crc": 7, "norm": 1.0}, 2)
    t.record(4, "b", {"crc": 8, "norm": 1.0, "consistent": False}, 2)
    # LATCHED: a later clean-looking single report still hears about it
    out = t.record(6, "a", {"crc": 9, "norm": 1.0}, 2)
    assert out["diverged"] and out["event"]["step"] == 4


def test_bitflip_rule_flips_exactly_one_element(monkeypatch):
    monkeypatch.setenv("PADDLE_PS_FAULT_SPEC", "bitflip:myphase:2:5")
    fl.set_flags({"FLAGS_ps_fault_injection": True})
    faults.reset()
    try:
        a = np.ones(8, np.float32)
        same = faults.bitflip_point("myphase", a)
        assert same is a  # 1st arrival: untouched, same object
        flipped = faults.bitflip_point("myphase", a)
        assert flipped is not a
        diff = np.nonzero(flipped != a)[0]
        assert list(diff) == [5]
        assert np.isfinite(a).all()
        # one-shot: the rule is spent
        assert faults.bitflip_point("myphase", a) is a
        # wrong phase never fires
        assert faults.bitflip_point("other", a) is a
    finally:
        fl.set_flags({"FLAGS_ps_fault_injection": False})
        faults.reset()


def test_coordinator_numerics_verbs_and_eviction(monkeypatch):
    monkeypatch.setenv("PADDLE_SDC_EVICT", "1")
    coord = Coordinator(lease_secs=5.0, retries_per_rank=1)
    coord.register("trainer0")
    coord.register("trainer1")
    good = {"crc": 10, "norm": 1.0, "consistent": True}
    bad = {"crc": 20, "norm": 9.0, "consistent": False}
    assert not coord.numerics_report("trainer0", 2, good, 2)["diverged"]
    out = coord.numerics_report("trainer1", 2, bad, 2)
    assert out["diverged"]
    assert out["event"]["odd_rank_out"] == ["trainer1"]
    evs = coord.drain_events()
    assert any(e.get("event") == "divergence" for e in evs)
    assert any(e.get("event") == "member_evicted"
               and e["tag"] == "trainer1" for e in evs)
    assert coord.members["trainer1"].evicted
    assert coord.numerics_status()["diverged"]


def test_executor_path_publishes_fingerprints(monkeypatch):
    """PADDLE_SDC_CHECK_EVERY + the coordinator endpoint make the
    Executor itself publish state fingerprints every K steps."""
    coord = Coordinator(lease_secs=5.0)
    srv, ep = serve_coordinator(coord)
    try:
        monkeypatch.setenv("PADDLE_COORDINATOR_ENDPOINT", ep)
        monkeypatch.setenv("PADDLE_SDC_CHECK_EVERY", "2")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        numerics._reset_for_tests()
        main, startup, loss = _linear_program()
        xb, yb = _data()
        _train(main, startup, loss, [{"x": xb, "y": yb}] * 4)
        st = coord.numerics_status()
        assert st["steps"], "no fingerprints reached the coordinator"
        assert not st["diverged"]  # one rank cannot diverge
        for reports in st["steps"].values():
            (fp,) = reports.values()
            assert fp["crc"] >= 0 and fp["norm"] > 0
    finally:
        stop_coordinator(srv)


@pytest.mark.slow
def test_bitflip_drill_two_ranks_names_corrupted_rank(tmp_path,
                                                      monkeypatch):
    """ISSUE 12 acceptance: 2 dp ranks, bitflip:sdc_apply:3 on rank 1
    only — the divergence event must name trainer1 within K steps of
    the flip, every rank must flight-dump, and PADDLE_SDC_EVICT must
    route trainer1 to the elastic eviction path."""
    K, flip_step = 2, 3
    out_dir = tmp_path / "out"
    trace_dir = tmp_path / "traces"
    out_dir.mkdir()
    trace_dir.mkdir()
    monkeypatch.setenv("PADDLE_SDC_EVICT", "1")
    coord = Coordinator(lease_secs=10.0, retries_per_rank=0)
    srv, ep = serve_coordinator(coord)
    try:
        base = dict(
            os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
            PADDLE_COORDINATOR_ENDPOINT=ep,
            PADDLE_SDC_CHECK_EVERY=str(K), SDC_TEST_STEPS="8",
            SDC_TEST_OUT=str(out_dir), PADDLE_TRACING="1",
            PADDLE_TRACE_DIR=str(trace_dir),
            FLAGS_ps_fault_injection="1",
            PADDLE_PS_FAULT_SPEC=f"bitflip:sdc_apply:{flip_step}",
            PADDLE_PS_FAULT_TAGS="trainer1", PADDLE_TRAINERS_NUM="2")
        procs = []
        for r in range(2):
            env = dict(base, PADDLE_TRAINER_ID=str(r),
                       PADDLE_TRAINER_TAG=f"trainer{r}")
            procs.append(subprocess.Popen(
                [sys.executable, WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err
        evs = coord.drain_events()
        div = [e for e in evs if e.get("event") == "divergence"]
        assert div, f"no divergence event in {evs}"
        first = div[0]
        # the corrupted rank is NAMED, within K steps of the flip
        assert first["odd_rank_out"] == ["trainer1"]
        assert flip_step <= first["step"] <= flip_step + K
        # all ranks flight-dumped
        dumps = sorted(f for f in os.listdir(trace_dir)
                       if f.startswith("flightrec"))
        assert dumps == ["flightrec.trainer0.json",
                         "flightrec.trainer1.json"]
        for f in dumps:
            rec = json.load(open(trace_dir / f))
            assert "sdc_divergence" in rec["reasons"]
        # eviction routed through the elastic path
        assert any(e.get("event") == "member_evicted"
                   and e["tag"] == "trainer1" for e in evs)
        # the UNCORRUPTED rank saw the verdict too (its own trace)
        t0 = [json.loads(l) for l in
              open(out_dir / "sdc.trainer0.jsonl")]
        assert any(v["diverged"] and v["odd"] == ["trainer1"]
                   for v in t0)
    finally:
        stop_coordinator(srv)


# ---------------------------------------------------------------------------
# /numericz + numtop CLI
# ---------------------------------------------------------------------------


def test_numericz_scrape(tmp_path, monkeypatch):
    fl.set_flags({"FLAGS_tensor_stats": True})
    debugz.stop()
    srv = debugz.serve(port=0, host="127.0.0.1")
    try:
        # build into the DEFAULT programs: /numericz reads the default
        # main program's watch roster (conftest gives each test fresh
        # defaults)
        x = layers.data("x", [8, 4], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        loss = layers.mean(
            layers.square_error_cost(layers.fc(x, 1), y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        xb, yb = _data()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        for _ in range(2):
            exe.run(feed={"x": xb, "y": yb}, fetch_list=[loss])
        port = srv.server_address[1]
        page = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/numericz", timeout=5
        ).read().decode())
        assert page["enabled"] is True
        assert page["watches"], "watch roster missing"
        assert page["history"], "sampled history missing"
        assert page["history"][-1]["event"] == "stats"
        # the index page names the endpoint
        root = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=5).read().decode()
        assert "/numericz" in root
    finally:
        debugz.stop()


def test_numtop_metrics_mode(tmp_path, capsys):
    path = str(tmp_path / "m.jsonl")
    sink.enable(path)
    fl.set_flags({"FLAGS_tensor_stats": True})
    main, startup, loss = _linear_program()
    xb, yb = _data()
    _train(main, startup, loss, [{"x": xb, "y": yb}] * 3)
    sink.disable()
    numtop = _load_tool("numtop")
    assert numtop.main(["--metrics", path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["watches"]
    grads = {k: v for k, v in out["watches"].items()
             if v["kind"] == "grad"}
    assert grads and all(w["samples"] == 3 and w["max_l2"] >= 0
                         for w in grads.values())
    # table mode renders and filters
    assert numtop.main(["--metrics", path, "--series",
                        "--watch", "fc_0"]) == 0
    text = capsys.readouterr().out
    assert "fc_0" in text and "watched series" in text


def test_numtop_doctor_mode(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("PADDLE_TRACE_DIR", str(tmp_path))
    fl.set_flags({"FLAGS_check_numerics": True})
    main, startup, loss, mul_idx, _ = _overflow_program()
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    xb, yb = _data()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(BadStepError) as ei:
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
    numtop = _load_tool("numtop")
    assert numtop.main(["--doctor", ei.value.dump_path]) == 0
    text = capsys.readouterr().out
    assert f"op#{mul_idx}" in text and "elementwise_mul" in text
    assert "user layer" in text


def test_numtop_empty_file_exits_one(tmp_path, capsys):
    path = tmp_path / "empty.jsonl"
    path.write_text("")
    numtop = _load_tool("numtop")
    assert numtop.main(["--metrics", str(path)]) == 1
