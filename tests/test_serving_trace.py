"""Request-lifecycle tracing and SLO attribution for the serving plane
(ISSUE 19).

Fast lane — shares the canonical tiny-decoder geometry with
test_kv_serving.py / test_gen_resume.py (same jits):
  * one trace over real TCP: client `generate` root -> rpc ->
    server:generate -> engine `gen_request` umbrella -> queue_wait /
    prefill / decode_step children -> retire, all on ONE trace_id with
    zero extra wire plumbing
  * pro-rata decode charging: co-batched slots' charged_ms sum to the
    measured step wall per step
  * failover-resume trace continuity: one trace, two `gen_request`
    residencies (the second marked resume=True)
  * client-observed ttft/tpot via `generate_stream(timings=...)`, skew
    bounded against the server-observed record
  * serve_ttft_ms / serve_tpot_ms SLO histograms with trace exemplars
    on the tail, surfaced in stats() quantiles and /metrics
  * PADDLE_TRACING off: wire bytes carry no `_trace` key, token stream
    bit-identical, zero spans recorded
  * debugz /servez scrape + servetop TTFT/TPOT/DEDUP columns (old
    layout intact for replicas predating the keys)
  * tools/reqtop.py reconstructs a request end-to-end from flightrec
    dumps (residency attribution, engine flight records)

Slow lane (tools/ci.sh serving-trace lane):
  * traced 16-request burst with an injected `stall:gen_decode_step`
    tail: >=90% of every completed request's engine wall time is
    attributed to spans, the stalled step's co-batched victims cite it
    through the serve_tpot_ms exemplar trace_id, and a no-tracing rerun
    produces token-bit-identical output
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.distributed import faults  # noqa: E402
from paddle_tpu.fluid import flags as fl  # noqa: E402
from paddle_tpu.fluid import layers  # noqa: E402
from paddle_tpu.inference import decode_model as dm  # noqa: E402
from paddle_tpu.inference import server as srvmod  # noqa: E402
from paddle_tpu.inference.client import InferenceClient  # noqa: E402
from paddle_tpu.inference.engine import (GenerationEngine,  # noqa: E402
                                         _SERVE_BUCKETS)
from paddle_tpu.inference.server import InferenceServer  # noqa: E402
from paddle_tpu.telemetry import get_registry  # noqa: E402
from paddle_tpu.telemetry import tracing  # noqa: E402

_REG = get_registry()

# canonical geometry shared with test_kv_serving.py / test_gen_resume.py
CFG = dm.DecoderConfig()          # vocab 64, d 32, L2 H2, max_seq 64
PAGES, PSZ, SLOTS = 24, 4, 2
PROMPT = [3, 9, 1, 4, 1, 5, 9]


def _mk_engine(kv=True, seed=1, **kw):
    kw.setdefault("n_pages", PAGES)
    kw.setdefault("page_size", PSZ)
    kw.setdefault("max_slots", SLOTS)
    if not kv:
        kw.pop("n_pages"), kw.pop("page_size")
    return GenerationEngine(dm.TinyDecoderLM(CFG, seed=seed),
                            kv_cache=kv, **kw)


def _slow_decode(monkeypatch, delay_s=0.01):
    real_step = dm.decode_step

    def slow_step(*a, **kw):
        time.sleep(delay_s)
        return real_step(*a, **kw)

    monkeypatch.setattr(dm, "decode_step", slow_step)


def _start_tcp(handler_obj):
    from paddle_tpu.distributed.ps_server import _Handler, _TCPServer

    srv = _TCPServer(("127.0.0.1", 0), _Handler)
    srv.ps = handler_obj
    threading.Thread(target=srv.serve_forever,
                     kwargs={"poll_interval": 0.05}, daemon=True).start()
    return srv, f"127.0.0.1:{srv.server_address[1]}"


def _stop_tcp(srv):
    srv.shutdown()
    srv.close_all_connections()
    srv.server_close()


def _spans():
    return tracing.finished_spans()


def _named(spans, name):
    return [s for s in spans if s["name"] == name]


def _settle(name, n, timeout=5.0):
    """Spans close on the engine loop thread a beat AFTER the result
    event fires (the final decode_step span's finally block): poll
    until `n` spans named `name` landed in the ring."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        spans = tracing.finished_spans()
        if len(_named(spans, name)) >= n:
            return spans
        time.sleep(0.005)
    return tracing.finished_spans()


def _reqtop():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import reqtop
    finally:
        sys.path.pop(0)
    return reqtop


@pytest.fixture(scope="module")
def gen_frozen():
    """Tiny frozen fc model for the server's infer path (the generate
    verbs only need SOME frozen model attached)."""
    from paddle_tpu import inference

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        pred = layers.fc(x, 2)
    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    return inference.freeze_program(main, scope=scope, feed_names=["x"],
                                    fetch_list=[pred])


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv(tracing.ENV_GATE, "1")
    tracing._reset_for_tests()
    yield
    tracing._reset_for_tests()


@pytest.fixture
def untraced(monkeypatch):
    monkeypatch.delenv(tracing.ENV_GATE, raising=False)
    tracing._reset_for_tests()
    yield
    tracing._reset_for_tests()


@pytest.fixture
def inject(monkeypatch):
    def _arm(spec: str):
        monkeypatch.setenv(faults.ENV_SPEC, spec)
        fl.set_flags({"FLAGS_ps_fault_injection": True})
        faults.reset()

    yield _arm
    fl.set_flags({"FLAGS_ps_fault_injection": False})
    faults.reset()


@pytest.fixture
def served(gen_frozen, monkeypatch):
    """One engine + InferenceServer + real TCP endpoint, torn down in
    order."""
    from paddle_tpu.inference import weight_sync as ws

    monkeypatch.setenv(ws.ENV_SYNC, "0")
    eng = _mk_engine(kv=True)
    inf = InferenceServer(gen_frozen, weight_subscribe=False, engine=eng)
    srv, ep = _start_tcp(inf)
    yield eng, inf, ep
    _stop_tcp(srv)
    inf.close()


# ---------------------------------------------------------------------------
# one trace, client -> queue -> prefill -> decode -> retire
# ---------------------------------------------------------------------------


def test_one_trace_client_to_retire_over_tcp(traced, served):
    """The tentpole wiring: the client root's trace_id rides the
    existing `_trace` RPC header, the handler thread dispatches inside
    `server:generate`, and the engine umbrella + every lifecycle child
    parent under it — one trace_id, client to retire."""
    eng, inf, ep = served
    cli = InferenceClient([ep])
    res = cli.generate(PROMPT, max_new_tokens=4)
    cli.close()
    assert len(res.tokens) == 4

    spans = _settle("decode_step", 3)
    (root,) = _named(spans, "generate")
    assert root["kind"] == "client" and root["status"] == "ok"
    tid = root["trace"]
    (hop,) = _named(spans, "server:generate")
    assert hop["trace"] == tid
    (gen,) = _named(spans, "gen_request")
    assert gen["trace"] == tid
    # the umbrella parents under the RPC hop: zero new wire plumbing
    assert gen["parent"] == hop["span"]
    a = gen["attrs"]
    assert a["outcome"] == "served" and a["tokens"] == 4
    assert a["prompt_len"] == len(PROMPT) and not a["resume"]
    (qw,) = _named(spans, "queue_wait")
    (pf,) = _named(spans, "prefill")
    steps = _named(spans, "decode_step")
    assert qw["parent"] == gen["span"] and qw["trace"] == tid
    assert pf["parent"] == gen["span"] and pf["trace"] == tid
    assert pf["attrs"]["positions"] == len(PROMPT)
    # prefill emits token 1; each later token is one decode step
    assert len(steps) >= 3
    assert all(s["parent"] == gen["span"] and s["trace"] == tid
               for s in steps)
    # the engine's own completion ledger carries the same trace
    recs = [r for r in tracing.request_records() if r["trace"] == tid]
    assert recs and recs[0]["outcome"] == "served"
    assert recs[0]["tokens"] == 4


def test_decode_step_prorata_charging_sums_to_step_wall(traced,
                                                        monkeypatch):
    """Every co-batched slot gets its own decode_step span; the step's
    measured wall is charged pro-rata, and the charges sum back to the
    wall — device time is attributed exactly once."""
    _slow_decode(monkeypatch, 0.005)
    eng = _mk_engine(kv=True)
    try:
        r1 = eng.submit(PROMPT, max_new_tokens=8)
        r2 = eng.submit([5, 1, 2], max_new_tokens=8)
        eng.result(r1, timeout=120)
        eng.result(r2, timeout=120)
    finally:
        eng.stop()
    by_step = {}
    for s in _named(_spans(), "decode_step"):
        by_step.setdefault(s["attrs"]["step"], []).append(s)
    shared = [g for g in by_step.values()
              if len(g) == 2 and all(s["attrs"]["batch"] == 2
                                     for s in g)]
    assert shared, "the two requests never co-batched"
    for group in shared:
        walls = {s["attrs"]["step_ms"] for s in group}
        assert len(walls) == 1  # one shared step wall
        (wall,) = walls
        charged = sum(s["attrs"]["charged_ms"] for s in group)
        assert charged == pytest.approx(wall, abs=0.01)
        # distinct slots, same step
        assert {s["attrs"]["slot"] for s in group} == {0, 1}


def test_failover_resume_is_one_trace_two_residencies(traced, gen_frozen,
                                                      monkeypatch):
    """Mid-stream replica death: the resume re-binds the ORIGINAL trace
    context, so one trace spans both replicas — a client root plus two
    gen_request residencies, the second marked resume."""
    from paddle_tpu.inference import weight_sync as ws

    monkeypatch.setenv(ws.ENV_SYNC, "0")
    eng_a = _mk_engine(kv=True, seed=1)
    eng_b = _mk_engine(kv=True, seed=1)
    inf_a = InferenceServer(gen_frozen, weight_subscribe=False,
                            engine=eng_a)
    inf_b = InferenceServer(gen_frozen, weight_subscribe=False,
                            engine=eng_b)
    srv_a, ep_a = _start_tcp(inf_a)
    srv_b, ep_b = _start_tcp(inf_b)
    a_stopped = False
    try:
        _slow_decode(monkeypatch, 0.02)
        cli = InferenceClient([ep_a, ep_b], deadline_secs=2.0)
        stream = cli.generate_stream(PROMPT, max_new_tokens=12,
                                     poll_s=0.005)
        got = list(next(stream))
        assert got
        _stop_tcp(srv_a)
        a_stopped = True
        for chunk in stream:
            got += chunk
        assert len(got) == 12
        cli.close()

        spans = _spans()
        (root,) = _named(spans, "generate_stream")
        tid = root["trace"]
        assert root["attrs"]["failovers"] == 1
        residencies = [s for s in _named(spans, "gen_request")
                       if s["trace"] == tid]
        residencies.sort(key=lambda s: s["ts"])
        assert len(residencies) == 2  # one per replica, ONE trace
        assert not residencies[0]["attrs"]["resume"]
        assert residencies[1]["attrs"]["resume"]
        # the resume carried the delivered prefix and finished the rest
        assert 0 < residencies[1]["attrs"]["resumed_from"] < 12
        assert residencies[1]["attrs"]["tokens"] == 12
        # the resume residency re-ran queue_wait + prefill on B
        resumed_kids = [s for s in spans
                        if s.get("parent") == residencies[1]["span"]]
        names = {s["name"] for s in resumed_kids}
        assert {"queue_wait", "prefill"} <= names
    finally:
        if not a_stopped:
            _stop_tcp(srv_a)
        _stop_tcp(srv_b)
        inf_a.close()
        inf_b.close()


# ---------------------------------------------------------------------------
# client-observed SLO timings (satellite: per-token timestamps)
# ---------------------------------------------------------------------------


def test_client_timings_skew_bounded_vs_server(traced, served,
                                               monkeypatch):
    """generate_stream(timings=...) hands the caller its OWN ttft/tpot;
    over fast loopback TCP the client-vs-server ttft skew is network +
    poll cadence — bounded, and never negative beyond clock grain."""
    eng, inf, ep = served
    _slow_decode(monkeypatch, 0.01)
    cli = InferenceClient([ep])
    timings: dict = {}
    got = []
    for chunk in cli.generate_stream(PROMPT, max_new_tokens=6,
                                     poll_s=0.005, timings=timings):
        got += chunk
    cli.close()
    assert len(got) == 6
    assert timings["tokens"] == 6
    assert len(timings["token_ts_ms"]) == 6
    assert timings["token_ts_ms"] == sorted(timings["token_ts_ms"])
    assert timings["ttft_ms"] is not None
    assert timings["tpot_avg_ms"] is not None and timings["tpot_avg_ms"] > 0
    # server-observed record for the same request (servez ledger)
    recent = eng.servez()["recent_slowest"]
    assert recent and recent[0]["outcome"] == "served"
    server_ttft = recent[0]["ttft_ms"]
    assert server_ttft is not None
    # client clock starts BEFORE the submit RPC and sees the token a
    # poll later: client ttft >= server ttft (minus clock grain), and
    # the skew on loopback stays well inside half a second
    skew = timings["ttft_ms"] - server_ttft
    assert skew > -5.0
    assert skew < 500.0


# ---------------------------------------------------------------------------
# SLO histograms + exemplars
# ---------------------------------------------------------------------------


def test_slo_histograms_carry_tail_exemplars(traced, monkeypatch):
    """serve_ttft_ms/serve_tpot_ms observe every request; a traced
    request pins its trace_id to the tail sample, and the stats() /
    /metrics surfaces hand it to the operator."""
    _REG.reset()  # the exemplar contest must start from this test
    _slow_decode(monkeypatch, 0.06)
    eng = _mk_engine(kv=True)
    try:
        req = eng.submit(PROMPT, max_new_tokens=5)
        eng.result(req, timeout=120)
        tid = next(s["trace"] for s in _named(_spans(), "gen_request"))
        st = eng.stats()
        for pfx in ("ttft", "tpot", "queue_wait"):
            assert st[f"{pfx}_p99_ms"] >= st[f"{pfx}_p50_ms"] >= 0.0
        assert st["tpot_p50_ms"] >= 25.0  # the slow decode is visible
        ex = _REG.histogram("serve_tpot_ms",
                            buckets=_SERVE_BUCKETS).exemplar
        assert ex is not None and ex["trace_id"] == tid
        assert ex["value"] >= 50.0
        # OpenMetrics exemplar syntax on the /metrics exposition
        prom = _REG.to_prometheus()
        assert f'# {{trace_id="{tid}"}}' in prom
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# flag-off bit-identity
# ---------------------------------------------------------------------------


def test_flag_off_wire_and_tokens_bit_identical(gen_frozen, monkeypatch):
    """PADDLE_TRACING=0: the wire carries no `_trace` key, zero spans
    are recorded, and the token stream is bit-identical to the traced
    run — tracing observes, never perturbs."""
    from paddle_tpu.inference import weight_sync as ws

    monkeypatch.setenv(ws.ENV_SYNC, "0")

    def run():
        eng = _mk_engine(kv=True, seed=1)
        inf = InferenceServer(gen_frozen, weight_subscribe=False,
                              engine=eng)
        srv, ep = _start_tcp(inf)
        try:
            cli = InferenceClient([ep])
            toks = cli.generate(PROMPT, max_new_tokens=8).tokens
            stream: list = []
            for chunk in cli.generate_stream([5, 1, 2],
                                             max_new_tokens=6,
                                             poll_s=0.005):
                stream += chunk
            cli.close()
            return toks, stream
        finally:
            _stop_tcp(srv)
            inf.close()

    monkeypatch.setenv(tracing.ENV_GATE, "1")
    tracing._reset_for_tests()
    try:
        want = run()
        assert _spans()  # the traced run really traced

        monkeypatch.delenv(tracing.ENV_GATE)
        tracing._reset_for_tests()
        seen = []
        orig = InferenceServer.handle

        def spy(self, method, kwargs):
            seen.append((method, dict(kwargs)))
            return orig(self, method, kwargs)

        monkeypatch.setattr(InferenceServer, "handle", spy)
        got = run()
        assert got == want
        assert seen and all("_trace" not in kw for _, kw in seen)
        assert _spans() == []
        assert tracing.request_records() == []
    finally:
        tracing._reset_for_tests()


# ---------------------------------------------------------------------------
# /servez + servetop columns
# ---------------------------------------------------------------------------


def test_debugz_servez_scrape(traced, served):
    eng, inf, ep = served
    cli = InferenceClient([ep])
    cli.generate(PROMPT, max_new_tokens=4)
    cli.close()
    from paddle_tpu.telemetry import debugz

    status, ctype, body = debugz._route("/servez")
    assert status == 200 and ctype == "application/json"
    page = json.loads(body)
    assert page["mode"] == "paged"
    assert page["max_slots"] == SLOTS
    assert "dedup_hits_total" in page
    rec = page["recent_slowest"][0]
    assert rec["outcome"] == "served" and rec["tokens"] == 4
    assert rec["trace"]  # traced run: the row resolves to a trace
    assert rec["total_ms"] >= rec["queue_ms"] >= 0.0
    # the index advertises the endpoint
    _, _, idx = debugz._route("/")
    assert b"/servez" in idx


def test_debugz_servez_404_without_engine(monkeypatch):
    monkeypatch.setattr(srvmod, "_ACTIVE", None)
    from paddle_tpu.telemetry import debugz

    status, _, body = debugz._route("/servez")
    assert status == 404
    assert b"no generation engine" in body


def test_servetop_slo_columns_and_old_layout():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import servetop
    finally:
        sys.path.pop(0)
    new_gen = {"tokens_total": 640, "tokens_per_s": 123.4,
               "decode_positions_total": 600,
               "prefill_positions_total": 40,
               "recompute_positions_total": 0,
               "shed_total": 0, "deadline_exceeded_total": 0,
               "queue_depth": 0, "resumed_total": 7,
               "preempted_total": 3,
               "ttft_p50_ms": 12.5, "ttft_p99_ms": 180.0,
               "tpot_p50_ms": 4.2, "tpot_p99_ms": 9.9,
               "dedup_hits_total": 2,
               "kv_pool": {"residency": 0.42, "prefix_hit_rate": 0.8}}
    old_gen = {k: v for k, v in new_gen.items()
               if k not in ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                            "tpot_p99_ms", "dedup_hits_total")}
    rows = [
        {"endpoint": "127.0.0.1:8500",
         "serving": {"served_total": 5, "weight_epoch": 2},
         "generation": new_gen},
        {"endpoint": "127.0.0.1:8501",  # replica predating the keys
         "serving": {"served_total": 5, "weight_epoch": 2},
         "generation": old_gen},
    ]
    text = servetop.render(rows)
    head, new_line, old_line = text.splitlines()
    for col in ("TTFT50", "TTFT99", "TPOT50", "TPOT99", "DEDUP"):
        assert col in head
    assert "12.5" in new_line and "180.0" in new_line
    assert "4.2" in new_line and f"{2:5d}" in new_line
    # the old replica keeps every pre-existing column and dashes the new
    assert "123.4" in old_line and f"{7:6d}" in old_line
    assert "12.5" not in old_line
    # same column positions either way
    assert old_line.index("42.0%") == new_line.index("42.0%")
    assert len(old_line.split()) == len(new_line.split())


# ---------------------------------------------------------------------------
# span export off the replica (ISSUE 20): engine spans drain through the
# OTLP trace push instead of only reaching disk via the flight recorder
# ---------------------------------------------------------------------------


def test_serving_spans_drain_through_otlp_push(traced):
    from paddle_tpu.telemetry import export

    posts = []

    class _Exp(export.PushExporter):
        def _post_once(self, body, ctype):
            posts.append(json.loads(body.decode()))

    eng = _mk_engine(kv=True)
    try:
        r = eng.result(eng.submit(PROMPT, max_new_tokens=3), timeout=120)
        assert len(r["tokens"]) == 3
        _settle("gen_request", 1)
        exp = _Exp("http://127.0.0.1:1/v1/traces", interval_s=3600,
                   body_fn=export._traces_body_fn(),
                   counter_prefix="traces")
        assert exp.flush() is True
        names = {s["name"] for p in posts
                 for s in p["resourceSpans"][0]["scopeSpans"][0]["spans"]}
        # the serving lifecycle left the replica: umbrella + children
        assert {"gen_request", "queue_wait", "prefill",
                "decode_step"} <= names
        exp.stop()
    finally:
        eng.stop()


def test_serve_arms_trace_push_from_env(traced, gen_frozen, monkeypatch):
    """server.serve mirrors ps_server.serve: PADDLE_TRACES_PUSH_URL
    arms the exporter at startup, and the teardown finally flushes it —
    the last requests' spans leave the replica before the process
    does. serve_forever is stubbed to one in-process generation so the
    whole serve() lifecycle (arm -> serve -> flush) runs inline."""
    from paddle_tpu.distributed import ps_server as psrv
    from paddle_tpu.telemetry import export

    posts = []

    class _Exp(export.PushExporter):
        def _post_once(self, body, ctype):
            posts.append(json.loads(body.decode()))

    monkeypatch.setenv(export.ENV_TRACES_URL, "http://127.0.0.1:1/x")
    monkeypatch.setattr(export, "PushExporter", _Exp)
    export.stop()  # reset the once-only arming latch
    eng = _mk_engine(kv=True)

    def fake_serve_forever(self, poll_interval=0.1):
        # inside serve(): the env URL must have armed the exporter
        assert export.active_traces() is not None
        r = eng.result(eng.submit(PROMPT, max_new_tokens=3),
                       timeout=120)
        assert len(r["tokens"]) == 3
        _settle("gen_request", 1)

    monkeypatch.setattr(psrv._TCPServer, "serve_forever",
                        fake_serve_forever)
    try:
        srvmod.serve(gen_frozen, port=0, host="127.0.0.1", engine=eng)
        names = {s["name"] for p in posts
                 for s in p["resourceSpans"][0]["scopeSpans"][0]["spans"]}
        # serve()'s teardown flushed the serving lifecycle off-replica
        assert {"gen_request", "prefill", "decode_step"} <= names
    finally:
        export.stop()


# ---------------------------------------------------------------------------
# reqtop: flight-recorder reconstruction
# ---------------------------------------------------------------------------


def test_reqtop_reconstructs_from_flightrec(traced, served, monkeypatch,
                                            tmp_path, capsys):
    eng, inf, ep = served
    monkeypatch.setenv(tracing.ENV_DIR, str(tmp_path))
    cli = InferenceClient([ep])
    cli.generate(PROMPT, max_new_tokens=4)
    cli.close()
    _settle("decode_step", 3)
    assert tracing.flight_dump("test_dump")

    reqtop = _reqtop()
    dumps = reqtop.load_dumps(str(tmp_path))
    assert len(dumps) == 1
    spans = reqtop.merged_spans(dumps)
    reqs = reqtop.requests_report(spans, reqtop.merged_requests(dumps))
    assert len(reqs) == 1
    r = reqs[0]
    assert r["root"] == "generate" and r["client_ms"] is not None
    assert r["n_residencies"] == 1
    res = r["residencies"][0]
    assert res["outcome"] == "served" and not res["resume"]
    assert res["decode_steps"] >= 3
    assert res["prefill_attrs"]["positions"] == len(PROMPT)
    assert res["attributed_frac"] is not None
    assert res["attributed_ms"] <= res["wall_ms"] * 1.02
    # the engine's own ledger rode the dump
    assert r["flight_records"]
    assert r["flight_records"][0]["outcome"] == "served"

    # CLI entry point: --json round-trips
    assert reqtop.main([str(tmp_path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["requests"][0]["trace"] == r["trace"]
    # human format renders without blowing up
    assert "engine residency" in reqtop.format_request(r)


def test_reqtop_empty_dir_is_an_error(tmp_path, capsys):
    reqtop = _reqtop()
    assert reqtop.main([str(tmp_path)]) == 1
    assert "no flightrec" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# slow lane: the ci.sh serving-trace drill
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_traced_burst_attribution_drill(monkeypatch, inject, tmp_path):
    """THE acceptance drill: a traced 16-request burst with one
    injected `stall:gen_decode_step` tail. Every completed request's
    engine wall time is >=90% attributed to spans, the stalled step's
    co-batched victims cite it through the serve_tpot_ms exemplar, and
    a no-tracing rerun is token-bit-identical."""
    monkeypatch.setenv(tracing.ENV_GATE, "1")
    monkeypatch.setenv(tracing.ENV_DIR, str(tmp_path))
    tracing._reset_for_tests()
    _REG.reset()  # the stall must own the tpot exemplar
    _slow_decode(monkeypatch, 0.004)
    # one fat tail mid-burst: 1.5s dwarfs even a cold decode_step jit
    # compile, so the stall owns the tail unambiguously
    inject("stall:gen_decode_step:20:1500")
    prompts = [[10 + i, 3, 7, (i % 5) + 1] for i in range(16)]

    def run_burst():
        eng = _mk_engine(kv=True, max_slots=4, n_pages=48,
                         queue_depth=32)
        try:
            reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
            return [eng.result(r, timeout=180)["tokens"] for r in reqs]
        finally:
            eng.stop()

    try:
        tokens_traced = run_burst()
        assert all(len(t) == 8 for t in tokens_traced)
        spans = _spans()

        # >=90% of every request's engine wall time attributed to spans
        reqtop = _reqtop()
        report = reqtop.requests_report(spans, {})
        assert len(report) == 16
        for r in report:
            for res in r["residencies"]:
                assert res["outcome"] == "served"
                assert res["attributed_frac"] >= 0.90, (
                    f"trace {r['trace']}: only "
                    f"{res['attributed_frac']:.1%} attributed")

        # the injected stall is visible as one shared fat step, and its
        # co-batched victims cite it through the tpot tail exemplar
        steps = _named(spans, "decode_step")
        worst = max(s["attrs"]["step_ms"] for s in steps)
        assert worst >= 1500.0
        stalled = [s for s in steps
                   if s["attrs"]["step_ms"] >= 0.8 * worst]
        stalled_idx = {s["attrs"]["step"] for s in stalled}
        assert len(stalled_idx) <= 2  # the stall, not general slowness
        victims = {s["trace"] for s in stalled}
        assert len(victims) >= 2  # co-batched: several requests paid
        ex = _REG.histogram("serve_tpot_ms",
                            buckets=_SERVE_BUCKETS).exemplar
        assert ex is not None and ex["trace_id"] in victims
        assert ex["value"] >= 1000.0

        # flightrec -> reqtop end-to-end on the dumped ring
        assert tracing.flight_dump("drill")
        dumps = reqtop.load_dumps(str(tmp_path))
        merged = reqtop.requests_report(reqtop.merged_spans(dumps),
                                        reqtop.merged_requests(dumps))
        assert merged and merged[0]["flight_records"]

        # no-tracing, no-fault rerun: token-bit-identical
        fl.set_flags({"FLAGS_ps_fault_injection": False})
        faults.reset()
        monkeypatch.delenv(tracing.ENV_GATE)
        tracing._reset_for_tests()
        tokens_plain = run_burst()
        assert tokens_plain == tokens_traced
        assert _spans() == []
    finally:
        tracing._reset_for_tests()
