"""Fused residual+LayerNorm Pallas kernel (ops/pallas/add_ln.py) vs the
jnp oracle — forward and gradients, interpret mode on CPU."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _oracle(x, y, scale, shift, eps=1e-5):
    s = (x + y if y is not None else x).astype(jnp.float32)
    mu = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True)
    out = (s - mu) * jax.lax.rsqrt(var + eps) * scale + shift
    return out.astype(x.dtype)


@pytest.mark.parametrize("shape", [(4, 32, 128), (8, 256)])
@pytest.mark.parametrize("with_y", [True, False])
def test_fused_add_ln_matches_oracle(shape, with_y):
    from paddle_tpu.ops.pallas.add_ln import fused_add_ln

    rng = np.random.RandomState(0)
    h = shape[-1]
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    y = jnp.asarray(rng.randn(*shape).astype(np.float32)) if with_y else None
    scale = jnp.asarray(rng.rand(h).astype(np.float32) + 0.5)
    shift = jnp.asarray(rng.randn(h).astype(np.float32))

    out = fused_add_ln(x, y, scale, shift)
    ref = _oracle(x, y, scale, shift)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_fused(*args):
        if with_y:
            x_, y_, sc, sh = args
            o = fused_add_ln(x_, y_, sc, sh)
        else:
            x_, sc, sh = args
            o = fused_add_ln(x_, None, sc, sh)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(*args):
        if with_y:
            x_, y_, sc, sh = args
            o = _oracle(x_, y_, sc, sh)
        else:
            x_, sc, sh = args
            o = _oracle(x_, None, sc, sh)
        return jnp.sum(o * jnp.cos(o))

    args = (x, y, scale, shift) if with_y else (x, scale, shift)
    g_fused = jax.grad(loss_fused, argnums=tuple(range(len(args))))(*args)
    g_ref = jax.grad(loss_ref, argnums=tuple(range(len(args))))(*args)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4)
    if with_y:
        # the residual add distributes the cotangent: dx == dy exactly
        np.testing.assert_array_equal(np.asarray(g_fused[0]),
                                      np.asarray(g_fused[1]))


def test_fused_add_ln_bf16():
    from paddle_tpu.ops.pallas.add_ln import fused_add_ln

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 128).astype(np.float32)).astype(jnp.bfloat16)
    y = jnp.asarray(rng.randn(8, 128).astype(np.float32)).astype(jnp.bfloat16)
    scale = jnp.ones((128,), jnp.float32)
    shift = jnp.zeros((128,), jnp.float32)
    out = fused_add_ln(x, y, scale, shift)
    assert out.dtype == jnp.bfloat16
    ref = _oracle(x, y, scale, shift)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


def test_encoder_stack_dispatches_fused_ln():
    """FORCE_PALLAS: the fused stack must route its residual+LN pairs
    through the kernel and still match the jnp composition."""
    from paddle_tpu.ops import attention
    from paddle_tpu.ops.pallas.add_ln import fused_ln_dispatch_ok

    assert not fused_ln_dispatch_ok((4, 32, 128))  # interpret off by default
    attention.FORCE_PALLAS = True
    try:
        assert fused_ln_dispatch_ok((4, 32, 128))
        assert not fused_ln_dispatch_ok((4, 32, 96))  # H % 128 != 0
    finally:
        attention.FORCE_PALLAS = False
