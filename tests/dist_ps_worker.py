"""Worker for tests/test_ps_dist.py: PS-embedding training whose loss
trace must match a single-process run exactly (the reference
TestDistBase contract, test_dist_base.py:506, applied to the
listen_and_serv/gRPC-analog data plane in distributed/ps_server.py).

Modes (env):
  PADDLE_PSERVERS_IP_PORT_LIST set  -> hosted table (RemoteTable client)
  unset                             -> in-process table (single-proc ref)
  PS_TEST_KILL_RANK=r               -> rank r exits(3) after KILL_STEP
                                       pushes (dead-trainer drill: the
                                       survivor must FAIL FAST on the
                                       server's sync barrier, not hang)

Each trainer sees the per-rank half of one fixed global batch; only the
PS table trains (the projection is frozen), so no dense-gradient
allreduce is needed and the trace depends on the table alone.
"""
import json
import os
import sys

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import ps
from paddle_tpu.fluid import layers

GLOBAL_B, DIM, NCLS, ROWS, KILL_STEP = 32, 16, 7, 5_000, 4
STEPS = int(os.environ.get("PS_TEST_STEPS", 12))


def main():
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    mode = os.environ.get("PS_TEST_MODE", "sync")
    kill_rank = int(os.environ.get("PS_TEST_KILL_RANK", -1))
    # crash-once drill (elastic restart): rank `kill_rank` dies at
    # KILL_STEP on attempt 0 only; the restarted group must finish
    # against the SURVIVING pserver (stale barrier round + partially
    # trained table)
    if (os.environ.get("PS_TEST_CRASH_ONCE") == "1"
            and int(os.environ.get("PADDLE_ELASTIC_RESTART", 0)) > 0):
        kill_rank = -1

    rng = np.random.RandomState(0)
    all_ids = rng.randint(0, ROWS, (GLOBAL_B,)).astype(np.int64)
    all_labels = (all_ids % NCLS).astype(np.int64)[:, None]
    per = GLOBAL_B // world
    ids = all_ids[rank * per:(rank + 1) * per]
    labels = all_labels[rank * per:(rank + 1) * per]

    table = ps.create_table("ps_dist_table", shape=(ROWS, DIM),
                            mode=mode, num_shards=4, optimizer="sgd",
                            learning_rate=0.5, seed=7,
                            geo_sync_steps=3)

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        w = layers.data("ids", [per], dtype="int64",
                        append_batch_size=False)
        y = layers.data("y", [per, 1], dtype="int64",
                        append_batch_size=False)
        emb = layers.distributed_embedding(w, "ps_dist_table")
        # frozen projection: deterministic across processes, so the loss
        # trace is a pure function of the (shared) table state
        proj = layers.fc(
            emb, NCLS,
            param_attr=fluid.ParamAttr(
                name="proj_w", trainable=False,
                initializer=fluid.initializer.UniformInitializer(
                    low=-0.3, high=0.3, seed=11)),
            bias_attr=False)
        loss = layers.mean(layers.softmax_with_cross_entropy(proj, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for step in range(STEPS):
        (lv,) = exe.run(main_prog, feed={"ids": ids, "y": labels},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(())))
        if rank == kill_rank and step + 1 == KILL_STEP:
            os._exit(3)  # simulated hard trainer death (no cleanup)
    if hasattr(table, "flush"):  # geo: drain pending deltas
        table.flush()

    trace_dir = os.environ.get("PADDLE_DIST_TRACE_DIR", ".")
    dense = table.to_dense()
    # replication drill observability: hedging/failover counters and the
    # gather tail latency as THIS trainer saw them (additive keys; the
    # pre-replication drills ignore them)
    from paddle_tpu import telemetry

    reg = telemetry.get_registry()
    hedges_issued = sum(
        reg.counter("ps_client_hedges_issued_total", verb=v).value
        for v in ("gather", "stats"))
    hedges_won = sum(
        reg.counter("ps_client_hedges_won_total", verb=v).value
        for v in ("gather", "stats"))
    with open(os.path.join(trace_dir, f"trace.{rank}.json"), "w") as f:
        json.dump({"losses": losses,
                   "table_sum": float(np.float64(dense.sum())),
                   "table_touched": dense[np.unique(all_ids)][:4].tolist(),
                   "hedges_issued": hedges_issued,
                   "hedges_won": hedges_won,
                   "failovers": reg.counter(
                       "ps_client_failovers_total").value,
                   # effective = what the training loop waited (hedging
                   # included); falls back to the raw per-RPC histogram
                   # in unreplicated runs where no hedged path exists
                   "gather_p95_ms": (
                       reg.histogram("ps_client_effective_read_ms",
                                     verb="gather").quantile(0.95)
                       if reg.histogram("ps_client_effective_read_ms",
                                        verb="gather").count
                       else reg.histogram("ps_client_rpc_ms",
                                          verb="gather").quantile(0.95))},
                  f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
