"""Per-op sweep: every registered op gets a numpy-oracle OpTest case or an
explicit, justified exemption (reference contract: tests/unittests/
op_test.py — ~700 test_*_op.py files; here one parameterized table).

test_coverage asserts CASES ∪ EXEMPT == registry.registered_ops().
"""
import numpy as np
import pytest

from paddle_tpu.ops import registry

from op_test import OpTest

R = np.random.RandomState  # shorthand


def f32(a):
    return np.asarray(a, np.float32)


def _pos(rng, *shape):
    """Positive, away from 0 (safe for log/sqrt/div grads)."""
    return f32(rng.uniform(0.3, 1.5, shape))


def _mix(rng, *shape):
    """Mixed sign, away from kinks at 0 (safe for abs/relu grads)."""
    return f32(rng.uniform(0.25, 1.25, shape) * np.where(rng.rand(*shape) < 0.5, -1, 1))


def _softmax(z, axis=-1):
    e = np.exp(z - z.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# ---------------------------------------------------------------------------
# case table: op_type -> list of zero-arg factories returning OpTest
# ---------------------------------------------------------------------------

CASES = {}


def case(op_type):
    def deco(fn):
        CASES.setdefault(op_type, []).append(fn)
        return fn

    return deco


def unary(op_type, np_fn, inp=_mix, grad=True, attrs=None, tol=1e-5, grad_tol=1e-2):
    def make():
        x = inp(R(7), 3, 5)
        return OpTest(
            op_type, {"X": x},
            lambda ins, a, fn=np_fn: {"Out": [f32(fn(ins["X"][0], a))]},
            attrs=attrs, grad=("X",) if grad else (), tol=tol, grad_tol=grad_tol,
        )

    CASES.setdefault(op_type, []).append(make)


# ---- activations / unary elementwise --------------------------------------
unary("abs", lambda x, a: np.abs(x))
unary("acos", lambda x, a: np.arccos(x), inp=lambda r, *s: f32(r.uniform(-0.8, 0.8, s)))
unary("asin", lambda x, a: np.arcsin(x), inp=lambda r, *s: f32(r.uniform(-0.8, 0.8, s)))
unary("atan", lambda x, a: np.arctan(x))
unary("ceil", lambda x, a: np.ceil(x), grad=False)
unary("floor", lambda x, a: np.floor(x), grad=False)
unary("round", lambda x, a: np.round(x), grad=False)
unary("sign", lambda x, a: np.sign(x), grad=False)
unary("cos", lambda x, a: np.cos(x))
unary("sin", lambda x, a: np.sin(x))
unary("tan", lambda x, a: np.tan(x))
unary("sinh", lambda x, a: np.sinh(x))
unary("cosh", lambda x, a: np.cosh(x))
unary("erf", lambda x, a: np.vectorize(__import__("math").erf)(x).astype(np.float32))
unary("exp", lambda x, a: np.exp(x))
unary("log", lambda x, a: np.log(x), inp=_pos)
unary("log2", lambda x, a: np.log2(x), inp=_pos)
unary("log10", lambda x, a: np.log10(x), inp=_pos)
unary("log1p", lambda x, a: np.log1p(x), inp=_pos)
unary("sqrt", lambda x, a: np.sqrt(x), inp=_pos)
unary("rsqrt", lambda x, a: 1.0 / np.sqrt(x), inp=_pos)
unary("square", lambda x, a: np.square(x))
unary("reciprocal", lambda x, a: 1.0 / x, inp=_pos)
unary("sigmoid", lambda x, a: 1 / (1 + np.exp(-x)))
unary("logsigmoid", lambda x, a: -np.log1p(np.exp(-x)))
unary("tanh", lambda x, a: np.tanh(x))
unary("relu", lambda x, a: np.maximum(x, 0))
unary("relu6", lambda x, a: np.clip(x, 0, 6.0))
unary("softplus", lambda x, a: np.log1p(np.exp(x)))
unary("softsign", lambda x, a: x / (1 + np.abs(x)))
unary("silu", lambda x, a: x / (1 + np.exp(-x)))
unary("swish", lambda x, a: x / (1 + np.exp(-x)))
unary("mish", lambda x, a: x * np.tanh(np.log1p(np.exp(x))))
unary("leaky_relu", lambda x, a: np.where(x > 0, x, 0.02 * x))
unary("elu", lambda x, a: np.where(x > 0, x, np.exp(x) - 1.0))
unary(
    "gelu",
    lambda x, a: x * 0.5 * (1 + np.vectorize(__import__("math").erf)(x / np.sqrt(2.0))),
    tol=1e-4,
)
unary("hard_sigmoid", lambda x, a: np.clip(0.2 * x + 0.5, 0, 1))
unary("hard_swish", lambda x, a: x * np.clip(x + 3.0, 0, 6.0) / 6.0)
unary("thresholded_relu", lambda x, a: np.where(x > 1.0, x, 0.0), inp=lambda r, *s: f32(r.uniform(0.5, 1.6, s)))
unary("hard_shrink", lambda x, a: np.where(np.abs(x) > 0.5, x, 0.0), inp=lambda r, *s: f32(r.uniform(0.7, 1.5, s)))
unary("soft_shrink", lambda x, a: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0), inp=lambda r, *s: f32(r.uniform(0.8, 1.5, s) * np.where(r.rand(*s) < 0.5, -1, 1)))
unary("scale", lambda x, a: x * 3.0 + 0.5, attrs={"scale": 3.0, "bias": 0.5})
unary("increment", lambda x, a: x + 2.0, attrs={"step": 2.0})
unary("assign", lambda x, a: x)
unary("pow", lambda x, a: np.power(x, 2.0), inp=_pos, attrs={"factor": 2.0})
unary("clip", lambda x, a: np.clip(x, -0.5, 0.5), attrs={"min": -0.5, "max": 0.5}, grad=False)
unary("logsumexp", lambda x, a: f32([np.log(np.sum(np.exp(x)))]), attrs={"axis": [], "keepdim": False})
unary("softmax", lambda x, a: _softmax(x))
unary("log_softmax", lambda x, a: np.log(_softmax(x)))
unary("mean", lambda x, a: f32([x.mean()]))
unary("squared_l2_norm", lambda x, a: f32([np.sum(x * x)]))


@case("cast")
def _cast():
    x = _mix(R(3), 3, 4)
    return OpTest(
        "cast", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].astype(np.int32)]},
        attrs={"in_dtype": np.dtype("float32"), "out_dtype": np.dtype("int32")},
    )


# ---- binary elementwise ----------------------------------------------------


def binary(op_type, np_fn, y_inp=None, grad=("X", "Y"), attrs=None):
    def make():
        rng = R(11)
        x = _mix(rng, 3, 4)
        if y_inp is None:
            # keep |x-y| >= 0.15: min/max kinks stay out of finite-diff reach
            y = x + f32(np.where(rng.rand(3, 4) < 0.5, -1, 1) * rng.uniform(0.15, 0.8, (3, 4)))
        else:
            y = y_inp(rng, 3, 4)
        return OpTest(
            op_type, {"X": x, "Y": y},
            lambda ins, a, fn=np_fn: {"Out": [fn(ins["X"][0], ins["Y"][0])]},
            attrs=attrs, grad=grad,
        )

    CASES.setdefault(op_type, []).append(make)


binary("elementwise_add", lambda x, y: x + y)
binary("elementwise_sub", lambda x, y: x - y)
binary("elementwise_mul", lambda x, y: x * y)
binary("elementwise_div", lambda x, y: x / y, y_inp=_pos)
binary("elementwise_min", lambda x, y: np.minimum(x, y))
binary("elementwise_max", lambda x, y: np.maximum(x, y))
binary("maximum", lambda x, y: np.maximum(x, y))
binary("minimum", lambda x, y: np.minimum(x, y))


@case("elementwise_pow")
def _epow():
    rng = R(2)
    x, y = _pos(rng, 3, 4), _pos(rng, 3, 4)
    return OpTest(
        "elementwise_pow", {"X": x, "Y": y},
        lambda ins, a: {"Out": [np.power(ins["X"][0], ins["Y"][0])]},
        grad=("X", "Y"),
    )


@case("elementwise_mod")
def _emod():
    rng = R(5)
    x = rng.randint(1, 50, (3, 4)).astype(np.int32)
    y = rng.randint(1, 7, (3, 4)).astype(np.int32)
    return OpTest(
        "elementwise_mod", {"X": x, "Y": y},
        lambda ins, a: {"Out": [np.mod(ins["X"][0], ins["Y"][0])]},
    )


@case("elementwise_floordiv")
def _efdiv():
    rng = R(5)
    x = rng.randint(1, 50, (3, 4)).astype(np.int32)
    y = rng.randint(1, 7, (3, 4)).astype(np.int32)
    return OpTest(
        "elementwise_floordiv", {"X": x, "Y": y},
        lambda ins, a: {"Out": [ins["X"][0] // ins["Y"][0]]},
    )


@case("elementwise_add")
def _eadd_axis():
    """paddle axis-broadcast: y [4] into x [2,4,3] at axis=1."""
    rng = R(13)
    x = _mix(rng, 2, 4, 3)
    y = _mix(rng, 4)
    return OpTest(
        "elementwise_add", {"X": x, "Y": y},
        lambda ins, a: {"Out": [ins["X"][0] + ins["Y"][0].reshape(1, 4, 1)]},
        attrs={"axis": 1}, grad=("X", "Y"),
    )


@case("sum")
def _sum():
    rng = R(17)
    xs = [_mix(rng, 3, 4) for _ in range(3)]
    return OpTest(
        "sum", {"X": xs},
        lambda ins, a: {"Out": [ins["X"][0] + ins["X"][1] + ins["X"][2]]},
        grad=("X",),
    )


# ---- compare / logical -----------------------------------------------------


def cmp_case(op_type, np_fn):
    def make():
        rng = R(23)
        x = rng.randint(0, 3, (3, 4)).astype(np.float32)
        y = rng.randint(0, 3, (3, 4)).astype(np.float32)
        return OpTest(
            op_type, {"X": x, "Y": y},
            lambda ins, a, fn=np_fn: {"Out": [fn(ins["X"][0], ins["Y"][0])]},
        )

    CASES.setdefault(op_type, []).append(make)


cmp_case("equal", np.equal)
cmp_case("not_equal", np.not_equal)
cmp_case("less_than", np.less)
cmp_case("less_equal", np.less_equal)
cmp_case("greater_than", np.greater)
cmp_case("greater_equal", np.greater_equal)


def logical_case(op_type, np_fn, nin=2):
    def make():
        rng = R(29)
        x = rng.rand(3, 4) > 0.5
        y = rng.rand(3, 4) > 0.5
        ins = {"X": x} if nin == 1 else {"X": x, "Y": y}
        return OpTest(
            op_type, ins,
            lambda i, a, fn=np_fn: {
                "Out": [fn(i["X"][0]) if nin == 1 else fn(i["X"][0], i["Y"][0])]
            },
        )

    CASES.setdefault(op_type, []).append(make)


logical_case("logical_and", np.logical_and)
logical_case("logical_or", np.logical_or)
logical_case("logical_xor", np.logical_xor)
logical_case("logical_not", np.logical_not, nin=1)


@case("allclose")
def _allclose():
    x = f32([[1.0, 2.0], [3.0, 4.0]])
    return OpTest(
        "allclose", {"Input": x, "Other": x + 1e-7},
        lambda ins, a: {"Out": [np.asarray(True)]},
        attrs={"rtol": 1e-5, "atol": 1e-8},
    )


def isx_case(op_type, np_fn, reduced):
    def make():
        x = f32([[1.0, np.inf], [np.nan, 2.0]])
        if reduced:
            oracle = lambda ins, a, fn=np_fn: {"Out": [np.asarray([fn(ins["X"][0]).any() if op_type != "isfinite" else fn(ins["X"][0]).all()])]}
        else:
            oracle = lambda ins, a, fn=np_fn: {"Out": [fn(ins["X"][0])]}
        return OpTest(op_type, {"X": x}, oracle)

    CASES.setdefault(op_type, []).append(make)


isx_case("isfinite", np.isfinite, True)
isx_case("isinf", np.isinf, True)
isx_case("isnan", np.isnan, True)
isx_case("isfinite_v2", np.isfinite, False)
isx_case("isinf_v2", np.isinf, False)
isx_case("isnan_v2", np.isnan, False)


# ---- reductions ------------------------------------------------------------


def reduce_case(op_type, np_fn, grad=True, boolean=False):
    def make():
        rng = R(31)
        x = (rng.rand(2, 3, 4) > 0.5) if boolean else _mix(rng, 2, 3, 4)
        return OpTest(
            op_type, {"X": x},
            lambda ins, a, fn=np_fn: {"Out": [fn(ins["X"][0], axis=1)]},
            attrs={"dim": [1], "keep_dim": False},
            grad=("X",) if grad else (),
        )

    def make_all():
        rng = R(37)
        x = (rng.rand(2, 3) > 0.5) if boolean else _pos(rng, 2, 3)
        return OpTest(
            op_type, {"X": x},
            lambda ins, a, fn=np_fn: {"Out": [np.asarray([fn(ins["X"][0])])]},
            attrs={"reduce_all": True, "keep_dim": False, "dim": [0]},
            grad=("X",) if grad else (),
        )

    CASES.setdefault(op_type, []).extend([make, make_all])


reduce_case("reduce_sum", np.sum)
reduce_case("reduce_mean", np.mean)
reduce_case("reduce_max", np.max)
reduce_case("reduce_min", np.min)
reduce_case("reduce_prod", np.prod)
reduce_case("reduce_all", np.all, grad=False, boolean=True)
reduce_case("reduce_any", np.any, grad=False, boolean=True)


@case("frobenius_norm")
def _frob():
    x = _mix(R(41), 3, 4)
    return OpTest(
        "frobenius_norm", {"X": x},
        lambda ins, a: {"Out": [f32([np.sqrt(np.sum(np.square(ins["X"][0])))])]},
        attrs={"reduce_all": True, "keep_dim": False}, grad=("X",),
    )


@case("p_norm")
def _pnorm():
    x = _mix(R(43), 3, 4)
    return OpTest(
        "p_norm", {"X": x},
        lambda ins, a: {"Out": [np.linalg.norm(ins["X"][0], ord=2, axis=-1).astype(np.float32)]},
        attrs={"porder": 2.0, "axis": -1, "keepdim": False}, grad=("X",),
    )


@case("norm")
def _norm():
    x = _mix(R(47), 3, 4)

    def oracle(ins, a):
        n = np.sqrt(np.sum(np.square(ins["X"][0]), axis=-1, keepdims=True) + 1e-10)
        return {"Out": [f32(ins["X"][0] / n)], "Norm": [f32(n)]}

    return OpTest(
        "norm", {"X": x}, oracle, attrs={"axis": -1},
        outputs={"Out": 1, "Norm": 1}, grad=("X",),
    )


@case("trace")
def _trace():
    x = _mix(R(53), 4, 4)
    return OpTest(
        "trace", {"Input": x},
        lambda ins, a: {"Out": [np.trace(ins["Input"][0]).astype(np.float32)]},
        grad=("Input",),
    )


# ---- matmul family ---------------------------------------------------------


@case("matmul")
def _matmul():
    rng = R(59)
    return OpTest(
        "matmul", {"X": _mix(rng, 3, 5), "Y": _mix(rng, 2, 5)},
        lambda ins, a: {"Out": [2.0 * ins["X"][0] @ ins["Y"][0].T]},
        attrs={"transpose_Y": True, "alpha": 2.0}, grad=("X", "Y"), grad_tol=2e-2,
    )


@case("matmul_v2")
def _matmul_v2():
    rng = R(61)
    return OpTest(
        "matmul_v2", {"X": _mix(rng, 2, 3, 5), "Y": _mix(rng, 2, 5, 4)},
        lambda ins, a: {"Out": [ins["X"][0] @ ins["Y"][0]]},
        grad=("X", "Y"), grad_tol=2e-2,
    )


@case("mul")
def _mul():
    rng = R(67)
    x, y = _mix(rng, 2, 3, 4), _mix(rng, 12, 5)
    return OpTest(
        "mul", {"X": x, "Y": y},
        lambda ins, a: {"Out": [(ins["X"][0].reshape(2, 12) @ ins["Y"][0]).reshape(2, 5)]},
        attrs={"x_num_col_dims": 1, "y_num_col_dims": 1}, grad=("X", "Y"), grad_tol=2e-2,
    )


@case("dot")
def _dot():
    rng = R(71)
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 4)
    return OpTest(
        "dot", {"X": x, "Y": y},
        lambda ins, a: {"Out": [np.sum(ins["X"][0] * ins["Y"][0], -1, keepdims=True)]},
        grad=("X", "Y"),
    )


@case("addmm")
def _addmm():
    rng = R(73)
    return OpTest(
        "addmm", {"Input": _mix(rng, 2, 4), "X": _mix(rng, 2, 3), "Y": _mix(rng, 3, 4)},
        lambda ins, a: {"Out": [0.5 * ins["Input"][0] + 2.0 * (ins["X"][0] @ ins["Y"][0])]},
        attrs={"Alpha": 2.0, "Beta": 0.5}, grad=("Input", "X", "Y"), grad_tol=2e-2,
    )


@case("kron")
def _kron():
    rng = R(79)
    return OpTest(
        "kron", {"X": _mix(rng, 2, 3), "Y": _mix(rng, 2, 2)},
        lambda ins, a: {"Out": [np.kron(ins["X"][0], ins["Y"][0])]},
        grad=("X", "Y"),
    )


@case("matrix_power")
def _matpow():
    x = f32(np.eye(3) * 0.8 + R(83).rand(3, 3) * 0.1)
    return OpTest(
        "matrix_power", {"X": x},
        lambda ins, a: {"Out": [np.linalg.matrix_power(ins["X"][0], 3).astype(np.float32)]},
        attrs={"n": 3}, grad=("X",), grad_tol=3e-2,
    )


@case("inverse")
def _inverse():
    x = f32(np.eye(3) + R(89).rand(3, 3) * 0.2)
    return OpTest(
        "inverse", {"Input": x},
        lambda ins, a: {"Output": [np.linalg.inv(ins["Input"][0]).astype(np.float32)]},
        outputs={"Output": 1}, grad=("Input",), grad_tol=3e-2, tol=1e-4,
    )


@case("cholesky")
def _cholesky():
    rng = R(97)
    a = f32(rng.rand(3, 3) * 0.3)
    x = a @ a.T + np.eye(3, dtype=np.float32)
    return OpTest(
        "cholesky", {"X": x},
        lambda ins, a_: {"Out": [np.linalg.cholesky(ins["X"][0]).astype(np.float32)]},
        tol=1e-4,
    )


@case("clip_by_norm")
def _clip_by_norm():
    x = _mix(R(101), 3, 4) * 5.0

    def oracle(ins, a):
        n = np.sqrt(np.sum(np.square(ins["X"][0])))
        return {"Out": [f32(ins["X"][0] * (1.0 / max(n / 1.0, 1.0)))]}

    return OpTest("clip_by_norm", {"X": x}, oracle, attrs={"max_norm": 1.0})


@case("prelu")
def _prelu():
    rng = R(103)
    x = _mix(rng, 2, 3)
    alpha = f32([0.25])
    return OpTest(
        "prelu", {"X": x, "Alpha": alpha},
        lambda ins, a: {"Out": [np.where(ins["X"][0] > 0, ins["X"][0], 0.25 * ins["X"][0])]},
        attrs={"mode": "all"}, grad=("X",),
    )


@case("maxout")
def _maxout():
    x = _mix(R(107), 2, 6, 3)
    return OpTest(
        "maxout", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].reshape(2, 2, 3, 3).max(axis=2)]},
        attrs={"groups": 3}, grad=("X",),
    )


# ---- manipulation ----------------------------------------------------------


@case("reshape")
def _reshape():
    x = _mix(R(109), 2, 6)
    return OpTest(
        "reshape", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].reshape(3, 4)]},
        attrs={"shape": [3, -1]}, grad=("X",),
    )


@case("reshape2")
def _reshape2():
    x = _mix(R(113), 2, 6)
    return OpTest(
        "reshape2", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].reshape(3, 4)]},
        attrs={"shape": [3, 4]}, outputs={"Out": 1, "XShape": 1}, grad=("X",),
    )


@case("transpose")
def _transpose():
    x = _mix(R(127), 2, 3, 4)
    return OpTest(
        "transpose", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].transpose(2, 0, 1)]},
        attrs={"axis": [2, 0, 1]}, grad=("X",),
    )


@case("transpose2")
def _transpose2():
    x = _mix(R(131), 2, 3)
    return OpTest(
        "transpose2", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].T]},
        attrs={"axis": [1, 0]}, outputs={"Out": 1, "XShape": 1}, grad=("X",),
    )


@case("concat")
def _concat():
    rng = R(137)
    xs = [_mix(rng, 2, 3), _mix(rng, 2, 2)]
    return OpTest(
        "concat", {"X": xs},
        lambda ins, a: {"Out": [np.concatenate(ins["X"], axis=1)]},
        attrs={"axis": 1}, grad=("X",),
    )


@case("split")
def _split():
    x = _mix(R(139), 2, 6)
    return OpTest(
        "split", {"X": x},
        lambda ins, a: {"Out": list(np.split(ins["X"][0], 3, axis=1))},
        attrs={"num": 3, "axis": 1}, outputs={"Out": 3}, grad=("X",),
    )


@case("slice")
def _slice():
    x = _mix(R(149), 4, 5)
    return OpTest(
        "slice", {"Input": x},
        lambda ins, a: {"Out": [ins["Input"][0][1:3, 0:4]]},
        attrs={"axes": [0, 1], "starts": [1, 0], "ends": [3, 4], "decrease_axis": []},
        grad=("Input",),
    )


@case("strided_slice")
def _strided_slice():
    x = _mix(R(151), 6, 5)
    return OpTest(
        "strided_slice", {"Input": x},
        lambda ins, a: {"Out": [ins["Input"][0][0:6:2]]},
        attrs={"axes": [0], "starts": [0], "ends": [6], "strides": [2]},
        grad=("Input",),
    )


@case("stack")
def _stack():
    rng = R(157)
    xs = [_mix(rng, 2, 3) for _ in range(3)]
    return OpTest(
        "stack", {"X": xs},
        lambda ins, a: {"Y": [np.stack(ins["X"], axis=1)]},
        attrs={"axis": 1}, outputs={"Y": 1}, grad=("X",),
    )


@case("unstack")
def _unstack():
    x = _mix(R(163), 3, 2, 4)
    return OpTest(
        "unstack", {"X": x},
        lambda ins, a: {"Y": [ins["X"][0][i] for i in range(3)]},
        attrs={"axis": 0, "num": 3}, outputs={"Y": 3}, grad=("X",),
    )


@case("unbind")
def _unbind():
    x = _mix(R(167), 2, 3, 2)
    return OpTest(
        "unbind", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0][:, i] for i in range(3)]},
        attrs={"axis": 1}, outputs={"Out": 3}, grad=("X",),
    )


@case("squeeze")
def _squeeze():
    x = _mix(R(173), 2, 1, 3)
    return OpTest(
        "squeeze", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].squeeze(1)]},
        attrs={"axes": [1]}, grad=("X",),
    )


@case("squeeze2")
def _squeeze2():
    x = _mix(R(179), 2, 1, 3)
    return OpTest(
        "squeeze2", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].squeeze(1)]},
        attrs={"axes": [1]}, outputs={"Out": 1, "XShape": 1}, grad=("X",),
    )


@case("unsqueeze")
def _unsqueeze():
    x = _mix(R(181), 2, 3)
    return OpTest(
        "unsqueeze", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0][:, None, :]]},
        attrs={"axes": [1]}, grad=("X",),
    )


@case("unsqueeze2")
def _unsqueeze2():
    x = _mix(R(191), 2, 3)
    return OpTest(
        "unsqueeze2", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0][:, None, :]]},
        attrs={"axes": [1]}, outputs={"Out": 1, "XShape": 1}, grad=("X",),
    )


@case("flatten")
def _flatten():
    x = _mix(R(193), 2, 3, 4)
    return OpTest(
        "flatten", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].reshape(2, 12)]},
        attrs={"axis": 1}, grad=("X",),
    )


@case("flatten2")
def _flatten2():
    x = _mix(R(197), 2, 3, 4)
    return OpTest(
        "flatten2", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].reshape(2, 12)]},
        attrs={"axis": 1}, outputs={"Out": 1, "XShape": 1}, grad=("X",),
    )


@case("flatten_contiguous_range")
def _flatten_cr():
    x = _mix(R(199), 2, 3, 4, 2)
    return OpTest(
        "flatten_contiguous_range", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].reshape(2, 12, 2)]},
        attrs={"start_axis": 1, "stop_axis": 2},
        outputs={"Out": 1, "XShape": 1}, grad=("X",),
    )


@case("expand")
def _expand():
    x = _mix(R(211), 2, 3)
    return OpTest(
        "expand", {"X": x},
        lambda ins, a: {"Out": [np.tile(ins["X"][0], (2, 1))]},
        attrs={"expand_times": [2, 1]}, grad=("X",),
    )


@case("expand_v2")
def _expand_v2():
    x = _mix(R(223), 1, 3)
    return OpTest(
        "expand_v2", {"X": x},
        lambda ins, a: {"Out": [np.broadcast_to(ins["X"][0], (4, 3))]},
        attrs={"shape": [4, 3]}, grad=("X",),
    )


@case("expand_as")
def _expand_as():
    rng = R(227)
    x, tgt = _mix(rng, 1, 3), _mix(rng, 4, 3)
    return OpTest(
        "expand_as", {"X": x, "target_tensor": tgt},
        lambda ins, a: {"Out": [np.broadcast_to(ins["X"][0], (4, 3))]},
        grad=("X",),
    )


@case("tile")
def _tile():
    x = _mix(R(229), 2, 3)
    return OpTest(
        "tile", {"X": x},
        lambda ins, a: {"Out": [np.tile(ins["X"][0], (2, 2))]},
        attrs={"repeat_times": [2, 2]}, grad=("X",),
    )


@case("gather")
def _gather():
    rng = R(233)
    x = _mix(rng, 5, 3)
    idx = np.asarray([0, 2, 4], np.int32)
    return OpTest(
        "gather", {"X": x, "Index": idx},
        lambda ins, a: {"Out": [ins["X"][0][ins["Index"][0]]]},
        grad=("X",),
    )


@case("gather_nd")
def _gather_nd():
    rng = R(239)
    x = _mix(rng, 3, 4)
    idx = np.asarray([[0, 1], [2, 3]], np.int32)
    return OpTest(
        "gather_nd", {"X": x, "Index": idx},
        lambda ins, a: {"Out": [f32([ins["X"][0][0, 1], ins["X"][0][2, 3]])]},
        grad=("X",),
    )


@case("scatter")
def _scatter():
    rng = R(241)
    x = _mix(rng, 5, 3)
    ids = np.asarray([1, 3], np.int32)
    upd = _mix(rng, 2, 3)

    def oracle(ins, a):
        out = ins["X"][0].copy()
        out[ins["Ids"][0]] = ins["Updates"][0]
        return {"Out": [out]}

    return OpTest(
        "scatter", {"X": x, "Ids": ids, "Updates": upd}, oracle,
        attrs={"overwrite": True}, grad=("X", "Updates"),
    )


@case("scatter_nd_add")
def _scatter_nd_add():
    rng = R(251)
    x = _mix(rng, 4, 3)
    idx = np.asarray([[1], [3]], np.int32)
    upd = _mix(rng, 2, 3)

    def oracle(ins, a):
        out = ins["X"][0].copy()
        out[1] += ins["Updates"][0][0]
        out[3] += ins["Updates"][0][1]
        return {"Out": [out]}

    return OpTest(
        "scatter_nd_add", {"X": x, "Index": idx, "Updates": upd}, oracle,
        grad=("X", "Updates"),
    )


@case("pad")
def _pad():
    x = _mix(R(257), 2, 3)
    return OpTest(
        "pad", {"X": x},
        lambda ins, a: {"Out": [np.pad(ins["X"][0], [(1, 0), (0, 2)], constant_values=0.5)]},
        attrs={"paddings": [1, 0, 0, 2], "pad_value": 0.5}, grad=("X",),
    )


@case("pad2d")
def _pad2d():
    x = _mix(R(263), 2, 3, 4, 4)
    return OpTest(
        "pad2d", {"X": x},
        lambda ins, a: {
            "Out": [np.pad(ins["X"][0], [(0, 0), (0, 0), (1, 2), (0, 1)])]
        },
        attrs={"paddings": [1, 2, 0, 1], "mode": "constant", "pad_value": 0.0},
        grad=("X",),
    )


@case("pad3d")
def _pad3d():
    x = _mix(R(269), 1, 2, 3, 3, 3)
    return OpTest(
        "pad3d", {"X": x},
        lambda ins, a: {
            "Out": [np.pad(ins["X"][0], [(0, 0), (0, 0), (1, 1), (1, 0), (0, 1)])]
        },
        attrs={"paddings": [0, 1, 1, 0, 1, 1], "mode": "constant", "value": 0.0},
        grad=("X",),
    )


@case("flip")
def _flip():
    x = _mix(R(271), 2, 3)
    return OpTest(
        "flip", {"X": x},
        lambda ins, a: {"Out": [np.flip(ins["X"][0], axis=(1,))]},
        attrs={"axis": [1]}, grad=("X",),
    )


@case("roll")
def _roll():
    x = _mix(R(277), 2, 4)
    return OpTest(
        "roll", {"X": x},
        lambda ins, a: {"Out": [np.roll(ins["X"][0], 1, axis=1)]},
        attrs={"shifts": [1], "axis": [1]}, grad=("X",),
    )


@case("where")
def _where():
    rng = R(281)
    cond = rng.rand(3, 4) > 0.5
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 4)
    return OpTest(
        "where", {"Condition": cond, "X": x, "Y": y},
        lambda ins, a: {"Out": [np.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]},
        grad=("X", "Y"),
    )


@case("arg_max")
def _arg_max():
    x = _mix(R(283), 3, 5)
    return OpTest(
        "arg_max", {"X": x},
        lambda ins, a: {"Out": [np.argmax(ins["X"][0], -1)]},
        attrs={"axis": -1},
    )


@case("arg_min")
def _arg_min():
    x = _mix(R(293), 3, 5)
    return OpTest(
        "arg_min", {"X": x},
        lambda ins, a: {"Out": [np.argmin(ins["X"][0], -1)]},
        attrs={"axis": -1},
    )


@case("argsort")
def _argsort():
    x = _mix(R(307), 3, 5)

    def oracle(ins, a):
        idx = np.argsort(ins["X"][0], -1)
        return {"Out": [np.take_along_axis(ins["X"][0], idx, -1)], "Indices": [idx]}

    return OpTest(
        "argsort", {"X": x}, oracle, attrs={"axis": -1},
        outputs={"Out": 1, "Indices": 1}, grad=("X",),
    )


@case("top_k")
def _top_k():
    x = f32(R(311).permutation(np.arange(18) * 0.3 - 2.0).reshape(3, 6))

    def oracle(ins, a):
        idx = np.argsort(-ins["X"][0], -1)[:, :2]
        return {"Out": [np.take_along_axis(ins["X"][0], idx, -1)], "Indices": [idx]}

    return OpTest(
        "top_k", {"X": x}, oracle, attrs={"k": 2},
        outputs={"Out": 1, "Indices": 1}, grad=("X",),
    )


@case("top_k_v2")
def _top_k_v2():
    x = f32(R(313).permutation(np.arange(18) * 0.3 - 2.0).reshape(3, 6))

    def oracle(ins, a):
        idx = np.argsort(-ins["X"][0], -1)[:, :2]
        return {"Out": [np.take_along_axis(ins["X"][0], idx, -1)], "Indices": [idx]}

    return OpTest(
        "top_k_v2", {"X": x}, oracle, attrs={"k": 2, "axis": -1, "largest": True},
        outputs={"Out": 1, "Indices": 1}, grad=("X",),
    )


@case("cumsum")
def _cumsum():
    x = _mix(R(317), 3, 4)
    return OpTest(
        "cumsum", {"X": x},
        lambda ins, a: {"Out": [np.cumsum(ins["X"][0], axis=1)]},
        attrs={"axis": 1}, grad=("X",),
    )


@case("tril_triu")
def _tril_triu():
    x = _mix(R(331), 4, 4)
    return OpTest(
        "tril_triu", {"X": x},
        lambda ins, a: {"Out": [np.tril(ins["X"][0])]},
        attrs={"lower": True, "diagonal": 0}, grad=("X",),
    )


@case("diag_v2")
def _diag_v2():
    x = _mix(R(337), 4)
    return OpTest(
        "diag_v2", {"X": x},
        lambda ins, a: {"Out": [np.diag(ins["X"][0])]},
        attrs={"offset": 0, "padding_value": 0.0},
    )


@case("index_select")
def _index_select():
    rng = R(347)
    x = _mix(rng, 4, 3)
    idx = np.asarray([0, 2], np.int32)
    return OpTest(
        "index_select", {"X": x, "Index": idx},
        lambda ins, a: {"Out": [ins["X"][0][[0, 2]]]},
        attrs={"dim": 0}, grad=("X",),
    )


@case("take_along_axis")
def _take_along_axis():
    rng = R(349)
    x = _mix(rng, 3, 4)
    idx = rng.randint(0, 4, (3, 2)).astype(np.int32)
    return OpTest(
        "take_along_axis", {"Input": x, "Index": idx},
        lambda ins, a: {"Result": [np.take_along_axis(ins["Input"][0], ins["Index"][0], 1)]},
        attrs={"Axis": 1}, outputs={"Result": 1}, grad=("Input",),
    )


@case("meshgrid")
def _meshgrid():
    rng = R(353)
    xs = [_mix(rng, 3), _mix(rng, 4)]

    def oracle(ins, a):
        a_, b_ = np.meshgrid(ins["X"][0], ins["X"][1], indexing="ij")
        return {"Out": [a_, b_]}

    return OpTest("meshgrid", {"X": xs}, oracle, outputs={"Out": 2}, grad=("X",))


@case("shard_index")
def _shard_index():
    ids = np.asarray([[1], [7], [12], [19]], np.int32)

    def oracle(ins, a):
        x = ins["X"][0]
        shard = x // 10 == 1
        return {"Out": [np.where(shard, x % 10, -1).astype(x.dtype)]}

    return OpTest(
        "shard_index", {"X": ids}, oracle,
        attrs={"index_num": 20, "nshards": 2, "shard_id": 1, "ignore_value": -1},
    )


@case("one_hot")
def _one_hot():
    x = np.asarray([[0], [2], [1]], np.int32)

    def oracle(ins, a):
        return {"Out": [np.eye(3, dtype=np.float32)[ins["X"][0].reshape(-1)]]}

    return OpTest("one_hot", {"X": x}, oracle, attrs={"depth": 3})


@case("one_hot_v2")
def _one_hot_v2():
    x = np.asarray([0, 2, 1], np.int32)

    def oracle(ins, a):
        return {"Out": [np.eye(3, dtype=np.float32)[ins["X"][0]]]}

    return OpTest("one_hot_v2", {"X": x}, oracle, attrs={"depth": 3})


# ---- creation --------------------------------------------------------------


@case("fill_constant")
def _fill_constant():
    return OpTest(
        "fill_constant", {},
        lambda ins, a: {"Out": [np.full((2, 3), 1.5, np.float32)]},
        attrs={"shape": [2, 3], "value": 1.5, "dtype": np.dtype("float32")},
    )


@case("fill_constant_batch_size_like")
def _fill_cbsl():
    x = _mix(R(359), 4, 2)
    return OpTest(
        "fill_constant_batch_size_like", {"Input": x},
        lambda ins, a: {"Out": [np.full((4, 7), 2.0, np.float32)]},
        attrs={"shape": [1, 7], "value": 2.0, "dtype": np.dtype("float32"),
               "input_dim_idx": 0, "output_dim_idx": 0},
    )


@case("fill_zeros_like")
def _fill_zeros_like():
    x = _mix(R(367), 2, 3)
    return OpTest(
        "fill_zeros_like", {"X": x},
        lambda ins, a: {"Out": [np.zeros_like(ins["X"][0])]},
    )


@case("fill_any_like")
def _fill_any_like():
    x = _mix(R(373), 2, 3)
    return OpTest(
        "fill_any_like", {"X": x},
        lambda ins, a: {"Out": [np.full_like(ins["X"][0], 3.5)]},
        attrs={"value": 3.5},
    )


@case("eye")
def _eye():
    return OpTest(
        "eye", {},
        lambda ins, a: {"Out": [np.eye(3, 4, dtype=np.float32)]},
        attrs={"num_rows": 3, "num_columns": 4, "dtype": np.dtype("float32")},
    )


@case("assign_value")
def _assign_value():
    vals = [1.0, 2.0, 3.0, 4.0]
    return OpTest(
        "assign_value", {},
        lambda ins, a: {"Out": [f32(vals).reshape(2, 2)]},
        attrs={"shape": [2, 2], "dtype": np.dtype("float32"), "fp32_values": vals},
    )


@case("range")
def _range():
    return OpTest(
        "range", {},
        lambda ins, a: {"Out": [np.arange(1, 9, 2, np.int32)]},
        attrs={"start": 1, "end": 9, "step": 2, "dtype": np.dtype("int32")},
    )


@case("linspace")
def _linspace():
    return OpTest(
        "linspace", {},
        lambda ins, a: {"Out": [np.linspace(0.0, 1.0, 5).astype(np.float32)]},
        attrs={"start": 0.0, "stop": 1.0, "num": 5, "dtype": np.dtype("float32")},
    )


@case("shape")
def _shape():
    x = _mix(R(379), 2, 5)
    return OpTest(
        "shape", {"Input": x},
        lambda ins, a: {"Out": [np.asarray([2, 5], np.int32)]},
    )


# ---- nn: conv / pool / norm ------------------------------------------------


def _np_conv2d(x, w, stride=1, pad=0):
    n, cin, h, wd = x.shape
    cout, _, kh, kw = w.shape
    xp = np.pad(x, [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh, j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


@case("conv2d")
def _conv2d():
    rng = R(383)
    x = _mix(rng, 2, 3, 5, 5)
    w = _mix(rng, 4, 3, 3, 3) * 0.2
    return OpTest(
        "conv2d", {"Input": x, "Filter": w},
        lambda ins, a: {"Output": [_np_conv2d(ins["Input"][0], ins["Filter"][0], 1, 1)]},
        attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1},
        outputs={"Output": 1}, grad=("Input", "Filter"), tol=1e-4, grad_tol=2e-2,
    )


@case("conv3d")
def _conv3d():
    rng = R(389)
    x = _mix(rng, 1, 2, 3, 4, 4)
    w = _mix(rng, 3, 2, 2, 2, 2) * 0.2

    def oracle(ins, a):
        import jax.numpy as jnp
        import jax.lax as lax

        out = lax.conv_general_dilated(
            jnp.asarray(ins["Input"][0]), jnp.asarray(ins["Filter"][0]),
            (1, 1, 1), [(0, 0)] * 3,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )
        return {"Output": [np.asarray(out)]}

    # oracle via jax.lax on *numpy* inputs is independent of the Program
    # path under test (the executor+emitter), matching the reference's use
    # of scipy in conv oracles
    return OpTest(
        "conv3d", {"Input": x, "Filter": w}, oracle,
        attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0], "dilations": [1, 1, 1], "groups": 1},
        outputs={"Output": 1}, grad=("Input", "Filter"), tol=1e-4, grad_tol=2e-2,
    )


@case("depthwise_conv2d")
def _depthwise_conv2d():
    rng = R(397)
    x = _mix(rng, 1, 3, 5, 5)
    w = _mix(rng, 3, 1, 3, 3) * 0.3

    def oracle(ins, a):
        xx, ww = ins["Input"][0], ins["Filter"][0]
        out = np.zeros((1, 3, 3, 3), np.float32)
        for c in range(3):
            out[:, c:c + 1] = _np_conv2d(xx[:, c:c + 1], ww[c:c + 1])
        return {"Output": [out]}

    return OpTest(
        "depthwise_conv2d", {"Input": x, "Filter": w}, oracle,
        attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1]},
        outputs={"Output": 1}, grad=("Input", "Filter"), tol=1e-4, grad_tol=2e-2,
    )


@case("conv2d_transpose")
def _conv2d_transpose():
    rng = R(401)
    x = _mix(rng, 1, 2, 3, 3)
    w = _mix(rng, 2, 3, 2, 2) * 0.3

    def oracle(ins, a):
        xx, ww = ins["Input"][0], ins["Filter"][0]
        out = np.zeros((1, 3, 4, 4), np.float32)
        for i in range(3):
            for j in range(3):
                out[:, :, i:i + 2, j:j + 2] += np.einsum(
                    "nc,cohw->nohw", xx[:, :, i, j], ww
                )
        return {"Output": [out]}

    return OpTest(
        "conv2d_transpose", {"Input": x, "Filter": w}, oracle,
        attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1], "groups": 1},
        outputs={"Output": 1}, grad=("Input", "Filter"), tol=1e-4, grad_tol=2e-2,
    )


@case("pool2d")
def _pool2d_max():
    x = _mix(R(409), 1, 2, 4, 4)

    def oracle(ins, a):
        xx = ins["X"][0]
        out = np.zeros((1, 2, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                out[:, :, i, j] = xx[:, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2].max((2, 3))
        return {"Out": [out]}

    return OpTest(
        "pool2d", {"X": x}, oracle,
        attrs={"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]},
        grad=("X",),
    )


@case("pool2d")
def _pool2d_avg_global():
    x = _mix(R(419), 1, 2, 4, 4)
    return OpTest(
        "pool2d", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0].mean((2, 3), keepdims=True)]},
        attrs={"pooling_type": "avg", "global_pooling": True, "ksize": [1, 1]},
        grad=("X",),
    )


@case("batch_norm")
def _batch_norm():
    rng = R(421)
    x = _mix(rng, 3, 2, 4)
    scale, bias = _pos(rng, 2), _mix(rng, 2)
    mean, var = np.zeros(2, np.float32), np.ones(2, np.float32)

    def oracle(ins, a):
        xx = ins["X"][0]
        m = xx.mean((0, 2))
        v = xx.var((0, 2))
        y = (xx - m[None, :, None]) / np.sqrt(v[None, :, None] + 1e-5)
        y = y * ins["Scale"][0][None, :, None] + ins["Bias"][0][None, :, None]
        return {"Y": [f32(y)], "SavedMean": [f32(m)]}

    return OpTest(
        "batch_norm",
        {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
        oracle, attrs={"epsilon": 1e-5, "momentum": 0.9, "data_layout": "NCHW"},
        outputs={"Y": 1, "MeanOut": 1, "VarianceOut": 1, "SavedMean": 1, "SavedVariance": 1},
        tol=1e-4,
    )


@case("fused_conv_bn")
def _fused_conv_bn():
    # 1x1 NHWC so the numpy oracle is one einsum; the kernel-shape sweep
    # (strides, SAME/VALID, kxk, odd channels) lives in
    # tests/test_conv_bn_fusion.py
    rng = R(77)
    x = _mix(rng, 2, 4, 4, 3)
    w = _mix(rng, 5, 3, 1, 1)
    scale, bias = _pos(rng, 5), _mix(rng, 5)
    mean, var = np.zeros(5, np.float32), np.ones(5, np.float32)

    def oracle(ins, a):
        xx, ww = ins["Input"][0], ins["Filter"][0]
        z = np.einsum("nhwc,oc->nhwo", xx, ww[:, :, 0, 0])
        m = z.mean((0, 1, 2))
        v = z.var((0, 1, 2))
        y = (z - m) / np.sqrt(v + 1e-5) * ins["Scale"][0] + ins["Bias"][0]
        return {"Y": [f32(np.maximum(y, 0.0))], "SavedMean": [f32(m)]}

    return OpTest(
        "fused_conv_bn",
        {"Input": x, "Filter": w, "Scale": scale, "Bias": bias,
         "Mean": mean, "Variance": var},
        oracle,
        attrs={"epsilon": 1e-5, "momentum": 0.9, "data_format": "NHWC",
               "data_layout": "NHWC", "with_relu": True},
        outputs={"Y": 1, "MeanOut": 1, "VarianceOut": 1, "SavedMean": 1,
                 "SavedVariance": 1},
        tol=1e-4,
    )


@case("layer_norm")
def _layer_norm():
    rng = R(431)
    x = _mix(rng, 3, 4)
    scale, bias = _pos(rng, 4), _mix(rng, 4)

    def oracle(ins, a):
        xx = ins["X"][0]
        m = xx.mean(-1, keepdims=True)
        v = xx.var(-1, keepdims=True)
        y = (xx - m) / np.sqrt(v + 1e-5) * ins["Scale"][0] + ins["Bias"][0]
        return {"Y": [f32(y)]}

    return OpTest(
        "layer_norm", {"X": x, "Scale": scale, "Bias": bias}, oracle,
        attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
        outputs={"Y": 1, "Mean": 1, "Variance": 1},
        grad=("X", "Scale", "Bias"), tol=1e-4, grad_tol=2e-2,
    )


@case("group_norm")
def _group_norm():
    rng = R(433)
    x = _mix(rng, 2, 4, 3)
    scale, bias = _pos(rng, 4), _mix(rng, 4)

    def oracle(ins, a):
        xx = ins["X"][0].reshape(2, 2, 2, 3)
        m = xx.mean((2, 3), keepdims=True)
        v = xx.var((2, 3), keepdims=True)
        y = ((xx - m) / np.sqrt(v + 1e-5)).reshape(2, 4, 3)
        y = y * ins["Scale"][0][None, :, None] + ins["Bias"][0][None, :, None]
        return {"Y": [f32(y)]}

    return OpTest(
        "group_norm", {"X": x, "Scale": scale, "Bias": bias}, oracle,
        attrs={"groups": 2, "epsilon": 1e-5},
        outputs={"Y": 1, "Mean": 1, "Variance": 1},
        grad=("X",), tol=1e-4, grad_tol=2e-2,
    )


@case("instance_norm")
def _instance_norm():
    rng = R(439)
    x = _mix(rng, 2, 3, 4)

    def oracle(ins, a):
        xx = ins["X"][0]
        m = xx.mean(-1, keepdims=True)
        v = xx.var(-1, keepdims=True)
        return {"Y": [f32((xx - m) / np.sqrt(v + 1e-5))]}

    return OpTest(
        "instance_norm", {"X": x}, oracle, attrs={"epsilon": 1e-5},
        outputs={"Y": 1, "SavedMean": 1, "SavedVariance": 1},
        grad=("X",), tol=1e-4, grad_tol=2e-2,
    )


@case("dropout")
def _dropout_test_mode():
    x = _mix(R(443), 3, 4)
    return OpTest(
        "dropout", {"X": x},
        lambda ins, a: {"Out": [ins["X"][0] * 0.7]},
        attrs={"dropout_prob": 0.3, "is_test": True,
               "dropout_implementation": "downgrade_in_infer"},
        outputs={"Out": 1, "Mask": 1}, grad=("X",),
    )


@case("lookup_table")
def _lookup_table():
    rng = R(449)
    w = _mix(rng, 6, 3)
    ids = np.asarray([[0], [5], [2]], np.int32)
    return OpTest(
        "lookup_table", {"W": w, "Ids": ids},
        lambda ins, a: {"Out": [ins["W"][0][[0, 5, 2]]]},
        grad=("W",),
    )


@case("lookup_table_v2")
def _lookup_table_v2():
    rng = R(457)
    w = _mix(rng, 6, 3)
    ids = np.asarray([[0, 5], [2, 1]], np.int32)
    return OpTest(
        "lookup_table_v2", {"W": w, "Ids": ids},
        lambda ins, a: {"Out": [ins["W"][0][ins["Ids"][0]]]},
        grad=("W",),
    )


@case("embedding_with_scaled_gradient")
def _emb_scaled():
    rng = R(461)
    w = _mix(rng, 6, 3)
    ids = np.asarray([1, 4], np.int32)
    return OpTest(
        "embedding_with_scaled_gradient", {"W": w, "Ids": ids},
        lambda ins, a: {"Out": [ins["W"][0][ins["Ids"][0]]]},
        grad=("W",),
    )


# ---- losses ----------------------------------------------------------------


@case("softmax_with_cross_entropy")
def _swce():
    rng = R(463)
    logits = _mix(rng, 4, 5)
    label = rng.randint(0, 5, (4, 1)).astype(np.int32)

    def oracle(ins, a):
        sm = _softmax(ins["Logits"][0])
        lbl = ins["Label"][0].reshape(-1)
        loss = -np.log(sm[np.arange(4), lbl])[:, None]
        return {"Softmax": [f32(sm)], "Loss": [f32(loss)]}

    return OpTest(
        "softmax_with_cross_entropy", {"Logits": logits, "Label": label},
        oracle, outputs={"Softmax": 1, "Loss": 1}, grad=("Logits",),
    )


@case("cross_entropy")
def _cross_entropy():
    rng = R(467)
    x = _softmax(_mix(rng, 4, 5)).astype(np.float32)
    label = rng.randint(0, 5, (4, 1)).astype(np.int32)

    def oracle(ins, a):
        lbl = ins["Label"][0].reshape(-1)
        return {"Y": [f32(-np.log(ins["X"][0][np.arange(4), lbl]))[:, None]]}

    return OpTest(
        "cross_entropy", {"X": x, "Label": label}, oracle,
        outputs={"Y": 1}, grad=("X",),
    )


@case("cross_entropy2")
def _cross_entropy2():
    rng = R(479)
    x = _softmax(_mix(rng, 4, 5)).astype(np.float32)
    label = rng.randint(0, 5, (4, 1)).astype(np.int32)

    def oracle(ins, a):
        lbl = ins["Label"][0].reshape(-1)
        y = f32(-np.log(ins["X"][0][np.arange(4), lbl]))[:, None]
        return {"Y": [y], "MatchX": [np.exp(-y)]}

    return OpTest(
        "cross_entropy2", {"X": x, "Label": label}, oracle,
        outputs={"Y": 1, "XShape": 1, "MatchX": 1}, grad=("X",),
    )


@case("sigmoid_cross_entropy_with_logits")
def _scel():
    rng = R(487)
    x = _mix(rng, 3, 4)
    label = rng.randint(0, 2, (3, 4)).astype(np.float32)

    def oracle(ins, a):
        xx, ll = ins["X"][0], ins["Label"][0]
        loss = np.maximum(xx, 0) - xx * ll + np.log1p(np.exp(-np.abs(xx)))
        return {"Out": [f32(loss)]}

    return OpTest(
        "sigmoid_cross_entropy_with_logits", {"X": x, "Label": label},
        oracle, grad=("X",),
    )


@case("bce_loss")
def _bce():
    rng = R(491)
    x = f32(rng.uniform(0.1, 0.9, (3, 4)))
    label = rng.randint(0, 2, (3, 4)).astype(np.float32)

    def oracle(ins, a):
        xx, ll = ins["X"][0], ins["Label"][0]
        return {"Out": [f32(-(ll * np.log(xx) + (1 - ll) * np.log(1 - xx)))]}

    return OpTest("bce_loss", {"X": x, "Label": label}, oracle, grad=("X",))


@case("square_error_cost")
def _sec():
    rng = R(499)
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 4)
    return OpTest(
        "square_error_cost", {"X": x, "Y": y},
        lambda ins, a: {"Out": [np.square(ins["X"][0] - ins["Y"][0])]},
        grad=("X", "Y"),
    )


@case("smooth_l1_loss")
def _sl1():
    rng = R(503)
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 4)

    def oracle(ins, a):
        d = ins["X"][0] - ins["Y"][0]
        ad = np.abs(d)
        loss = np.where(ad < 1.0, 0.5 * d * d, ad - 0.5)
        return {"Out": [f32(loss.sum(1, keepdims=True))], "Diff": [f32(d)]}

    return OpTest(
        "smooth_l1_loss", {"X": x, "Y": y}, oracle,
        attrs={"sigma": 1.0}, outputs={"Out": 1, "Diff": 1}, grad=("X", "Y"),
    )


@case("huber_loss")
def _huber():
    rng = R(509)
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 4)

    def oracle(ins, a):
        r = ins["Y"][0] - ins["X"][0]
        ar = np.abs(r)
        loss = np.where(ar <= 1.0, 0.5 * r * r, ar - 0.5)
        return {"Out": [f32(loss)], "Residual": [f32(r)]}

    return OpTest(
        "huber_loss", {"X": x, "Y": y}, oracle, attrs={"delta": 1.0},
        outputs={"Out": 1, "Residual": 1}, grad=("X",),
    )


@case("log_loss")
def _log_loss():
    rng = R(521)
    p = f32(rng.uniform(0.2, 0.8, (4, 1)))
    l = rng.randint(0, 2, (4, 1)).astype(np.float32)

    def oracle(ins, a):
        pp, ll = ins["Predicted"][0], ins["Labels"][0]
        eps = 1e-4
        return {"Loss": [f32(-ll * np.log(pp + eps) - (1 - ll) * np.log(1 - pp + eps))]}

    return OpTest(
        "log_loss", {"Predicted": p, "Labels": l}, oracle,
        attrs={"epsilon": 1e-4}, outputs={"Loss": 1}, grad=("Predicted",),
    )


@case("kldiv_loss")
def _kldiv():
    rng = R(523)
    x = _mix(rng, 3, 4)
    t = _softmax(_mix(rng, 3, 4)).astype(np.float32)

    def oracle(ins, a):
        tt = ins["Target"][0]
        loss = np.where(tt > 0, tt * (np.log(tt) - ins["X"][0]), 0.0)
        return {"Loss": [f32([loss.mean()])]}

    return OpTest(
        "kldiv_loss", {"X": x, "Target": t}, oracle,
        attrs={"reduction": "mean"}, outputs={"Loss": 1}, grad=("X",),
    )


@case("label_smooth")
def _label_smooth():
    x = np.eye(4, dtype=np.float32)[[0, 2, 1]]
    return OpTest(
        "label_smooth", {"X": x},
        lambda ins, a: {"Out": [f32(0.9 * ins["X"][0] + 0.1 / 4)]},
        attrs={"epsilon": 0.1}, grad=("X",),
    )


@case("mse_loss")
def _mse():
    rng = R(541)
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 4)
    return OpTest(
        "mse_loss", {"X": x, "Y": y},
        lambda ins, a: {"Out": [f32([np.mean(np.square(ins["X"][0] - ins["Y"][0]))])]},
        grad=("X", "Y"),
    )


@case("margin_rank_loss")
def _mrl():
    rng = R(547)
    x1, x2 = _mix(rng, 4, 1), _mix(rng, 4, 1)
    label = np.where(rng.rand(4, 1) < 0.5, -1.0, 1.0).astype(np.float32)

    def oracle(ins, a):
        act = np.maximum(0.0, -ins["Label"][0] * (ins["X1"][0] - ins["X2"][0]) + 0.1)
        return {"Out": [f32(act)]}

    return OpTest(
        "margin_rank_loss", {"X1": x1, "X2": x2, "Label": label}, oracle,
        attrs={"margin": 0.1}, outputs={"Out": 1, "Activated": 1},
    )


@case("auc")
def _auc():
    rng = R(701)
    n, nt = 50, 64
    score = f32(rng.rand(n))
    pred = np.stack([1 - score, score], 1)
    label = (score + rng.randn(n) * 0.3 > 0.5).astype(np.int64)[:, None]
    stat = np.zeros((1, nt + 1), np.int64)

    def oracle(ins, a):
        sc = ins["Predict"][0][:, 1]
        lb = ins["Label"][0].reshape(-1)
        sp = np.zeros(nt + 1, np.int64)
        sn = np.zeros(nt + 1, np.int64)
        idx = np.clip((sc * nt).astype(np.int64), 0, nt)
        for i, l in zip(idx, lb):
            (sp if l > 0 else sn)[i] += 1
        pos = np.cumsum(sp[::-1]); neg = np.cumsum(sn[::-1])
        x = np.concatenate([[0], neg]); y = np.concatenate([[0], pos])
        area = np.sum((x[1:] - x[:-1]) * (y[1:] + y[:-1])) / 2.0
        auc_v = f32([area / max(pos[-1] * neg[-1], 1)])
        return {"AUC": [auc_v],
                "StatPosOut": [sp.reshape(1, -1)],
                "StatNegOut": [sn.reshape(1, -1)]}

    return OpTest(
        "auc", {"Predict": pred, "Label": label, "StatPos": stat, "StatNeg": stat},
        oracle, attrs={"num_thresholds": nt},
        outputs={"AUC": 1, "StatPosOut": 1, "StatNegOut": 1}, tol=1e-4,
    )


@case("accuracy")
def _accuracy():
    idx = np.asarray([[0, 1], [2, 3], [1, 0]], np.int64)
    label = np.asarray([[1], [0], [2]], np.int64)

    def oracle(ins, a):
        return {
            "Accuracy": [f32([1.0 / 3.0])],
            "Correct": [np.asarray([1], np.int32)],
            "Total": [np.asarray([3], np.int32)],
        }

    return OpTest(
        "accuracy", {"Indices": idx, "Label": label}, oracle,
        outputs={"Accuracy": 1, "Correct": 1, "Total": 1},
    )


# ---- optimizer update ops --------------------------------------------------


@case("sgd")
def _sgd():
    rng = R(557)
    p, g = _mix(rng, 3, 4), _mix(rng, 3, 4)
    lr = f32([0.1])
    return OpTest(
        "sgd", {"Param": p, "Grad": g, "LearningRate": lr},
        lambda ins, a: {"ParamOut": [ins["Param"][0] - 0.1 * ins["Grad"][0]]},
        outputs={"ParamOut": 1},
    )


@case("momentum")
def _momentum():
    rng = R(563)
    p, g, v = _mix(rng, 3), _mix(rng, 3), _mix(rng, 3)
    lr = f32([0.1])

    def oracle(ins, a):
        vo = 0.9 * ins["Velocity"][0] + ins["Grad"][0]
        return {"ParamOut": [f32(ins["Param"][0] - 0.1 * vo)], "VelocityOut": [f32(vo)]}

    return OpTest(
        "momentum", {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr},
        oracle, attrs={"mu": 0.9},
        outputs={"ParamOut": 1, "VelocityOut": 1},
    )


@case("adam")
def _adam():
    rng = R(569)
    p, g = _mix(rng, 4), _mix(rng, 4)
    m1, m2 = _mix(rng, 4) * 0.1, _pos(rng, 4) * 0.01
    b1p, b2p = f32([0.9]), f32([0.999])
    lr = f32([0.01])

    def oracle(ins, a):
        b1, b2, eps = 0.9, 0.999, 1e-8
        gg = ins["Grad"][0]
        m1o = b1 * ins["Moment1"][0] + (1 - b1) * gg
        m2o = b2 * ins["Moment2"][0] + (1 - b2) * gg * gg
        lr_t = 0.01 * np.sqrt(1 - ins["Beta2Pow"][0][0]) / (1 - ins["Beta1Pow"][0][0])
        po = ins["Param"][0] - lr_t * m1o / (np.sqrt(m2o) + eps)
        return {
            "ParamOut": [f32(po)], "Moment1Out": [f32(m1o)], "Moment2Out": [f32(m2o)],
            "Beta1PowOut": [f32([0.9 * 0.9])], "Beta2PowOut": [f32([0.999 * 0.999])],
        }

    return OpTest(
        "adam",
        {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
         "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": lr},
        oracle, attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
        outputs={"ParamOut": 1, "Moment1Out": 1, "Moment2Out": 1,
                 "Beta1PowOut": 1, "Beta2PowOut": 1},
        tol=1e-4,
    )


@case("adamw")
def _adamw():
    rng = R(571)
    p, g = _mix(rng, 4), _mix(rng, 4)
    m1, m2 = np.zeros(4, np.float32), np.zeros(4, np.float32)
    b1p, b2p = f32([0.9]), f32([0.999])
    lr = f32([0.01])

    def oracle(ins, a):
        b1, b2, eps = 0.9, 0.999, 1e-8
        gg = ins["Grad"][0]
        m1o = (1 - b1) * gg
        m2o = (1 - b2) * gg * gg
        lr_t = 0.01 * np.sqrt(1 - ins["Beta2Pow"][0][0]) / (1 - ins["Beta1Pow"][0][0])
        po = ins["Param"][0] - lr_t * m1o / (np.sqrt(m2o) + eps)
        po = po - 0.01 * 0.01 * ins["Param"][0]
        return {"ParamOut": [f32(po)]}

    return OpTest(
        "adamw",
        {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
         "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": lr},
        oracle, attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8, "coeff": 0.01},
        outputs={"ParamOut": 1, "Moment1Out": 1, "Moment2Out": 1,
                 "Beta1PowOut": 1, "Beta2PowOut": 1},
        tol=1e-4,
    )


@case("adamax")
def _adamax():
    rng = R(577)
    p, g = _mix(rng, 4), _mix(rng, 4)
    m, inf = np.zeros(4, np.float32), np.zeros(4, np.float32)
    b1p = f32([0.9])
    lr = f32([0.01])

    def oracle(ins, a):
        b1, b2, eps = 0.9, 0.999, 1e-8
        gg = ins["Grad"][0]
        mo = (1 - b1) * gg
        info = np.maximum(0.0, np.abs(gg))
        po = ins["Param"][0] - (0.01 / (1 - 0.9)) * mo / (info + eps)
        return {"ParamOut": [f32(po)], "MomentOut": [f32(mo)], "InfNormOut": [f32(info)]}

    return OpTest(
        "adamax",
        {"Param": p, "Grad": g, "Moment": m, "InfNorm": inf,
         "Beta1Pow": b1p, "LearningRate": lr},
        oracle, attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
        outputs={"ParamOut": 1, "MomentOut": 1, "InfNormOut": 1}, tol=1e-4,
    )


@case("adagrad")
def _adagrad():
    rng = R(587)
    p, g, m = _mix(rng, 4), _mix(rng, 4), _pos(rng, 4) * 0.1
    lr = f32([0.1])

    def oracle(ins, a):
        mo = ins["Moment"][0] + ins["Grad"][0] ** 2
        po = ins["Param"][0] - 0.1 * ins["Grad"][0] / (np.sqrt(mo) + 1e-6)
        return {"ParamOut": [f32(po)], "MomentOut": [f32(mo)]}

    return OpTest(
        "adagrad", {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
        oracle, attrs={"epsilon": 1e-6},
        outputs={"ParamOut": 1, "MomentOut": 1}, tol=1e-4,
    )


@case("decayed_adagrad")
def _decayed_adagrad():
    rng = R(593)
    p, g, m = _mix(rng, 4), _mix(rng, 4), _pos(rng, 4) * 0.1
    lr = f32([0.1])

    def oracle(ins, a):
        mo = 0.95 * ins["Moment"][0] + 0.05 * ins["Grad"][0] ** 2
        po = ins["Param"][0] - 0.1 * ins["Grad"][0] / (np.sqrt(mo) + 1e-6)
        return {"ParamOut": [f32(po)], "MomentOut": [f32(mo)]}

    return OpTest(
        "decayed_adagrad", {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
        oracle, attrs={"decay": 0.95, "epsilon": 1e-6},
        outputs={"ParamOut": 1, "MomentOut": 1}, tol=1e-4,
    )


@case("rmsprop")
def _rmsprop():
    rng = R(599)
    p, g = _mix(rng, 4), _mix(rng, 4)
    ms, mom = _pos(rng, 4) * 0.1, np.zeros(4, np.float32)
    lr = f32([0.01])

    def oracle(ins, a):
        ms_out = 0.95 * ins["MeanSquare"][0] + 0.05 * ins["Grad"][0] ** 2
        mo = 0.9 * ins["Moment"][0] + 0.01 * ins["Grad"][0] / np.sqrt(ms_out + 1e-6)
        return {
            "ParamOut": [f32(ins["Param"][0] - mo)],
            "MomentOut": [f32(mo)], "MeanSquareOut": [f32(ms_out)],
        }

    return OpTest(
        "rmsprop",
        {"Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom, "LearningRate": lr},
        oracle, attrs={"decay": 0.95, "epsilon": 1e-6, "momentum": 0.9},
        outputs={"ParamOut": 1, "MomentOut": 1, "MeanSquareOut": 1}, tol=1e-4,
    )


@case("lamb")
def _lamb():
    rng = R(601)
    p, g = _pos(rng, 4), _mix(rng, 4)
    m1, m2 = np.zeros(4, np.float32), np.zeros(4, np.float32)
    b1p, b2p = f32([0.9]), f32([0.999])
    lr = f32([0.01])

    def oracle(ins, a):
        b1, b2, eps, wd = 0.9, 0.999, 1e-6, 0.01
        gg = ins["Grad"][0]
        m1o = (1 - b1) * gg
        m2o = (1 - b2) * gg * gg
        mhat = m1o / (1 - 0.9)
        vhat = m2o / (1 - 0.999)
        r = mhat / (np.sqrt(vhat) + eps) + wd * ins["Param"][0]
        trust = np.linalg.norm(ins["Param"][0]) / np.linalg.norm(r)
        po = ins["Param"][0] - 0.01 * trust * r
        return {"ParamOut": [f32(po)], "Moment1Out": [f32(m1o)], "Moment2Out": [f32(m2o)]}

    return OpTest(
        "lamb",
        {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
         "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": lr},
        oracle, attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6, "weight_decay": 0.01},
        outputs={"ParamOut": 1, "Moment1Out": 1, "Moment2Out": 1,
                 "Beta1PowOut": 1, "Beta2PowOut": 1},
        tol=1e-4,
    )


@case("lars_momentum")
def _lars():
    rng = R(607)
    p, g, v = _pos(rng, 4), _mix(rng, 4), np.zeros(4, np.float32)
    lr = f32([0.1])

    def oracle(ins, a):
        mu, coeff, wd = 0.9, 0.001, 0.0005
        pn = np.linalg.norm(ins["Param"][0])
        gn = np.linalg.norm(ins["Grad"][0])
        local_lr = 0.1 * coeff * pn / (gn + wd * pn)
        vo = mu * ins["Velocity"][0] + local_lr * (ins["Grad"][0] + wd * ins["Param"][0])
        return {"ParamOut": [f32(ins["Param"][0] - vo)], "VelocityOut": [f32(vo)]}

    return OpTest(
        "lars_momentum",
        {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr},
        oracle, attrs={"mu": 0.9, "lars_coeff": 0.001, "lars_weight_decay": 0.0005},
        outputs={"ParamOut": 1, "VelocityOut": 1}, tol=1e-4,
    )


@case("ftrl")
def _ftrl():
    rng = R(613)
    p, g = _mix(rng, 4), _mix(rng, 4)
    sq, lin = _pos(rng, 4) * 0.1, np.zeros(4, np.float32)
    lr = f32([0.1])

    def oracle(ins, a):
        gg, pp = ins["Grad"][0], ins["Param"][0]
        sq0 = ins["SquaredAccumulator"][0]
        new_sq = sq0 + gg * gg
        sigma = (np.sqrt(new_sq) - np.sqrt(sq0)) / 0.1
        lin_out = ins["LinearAccumulator"][0] + gg - sigma * pp
        denom = np.sqrt(new_sq) / 0.1
        po = (np.clip(lin_out, 0, 0) - lin_out) / denom
        return {
            "ParamOut": [f32(po)], "SquaredAccumOut": [f32(new_sq)],
            "LinearAccumOut": [f32(lin_out)],
        }

    return OpTest(
        "ftrl",
        {"Param": p, "Grad": g, "SquaredAccumulator": sq,
         "LinearAccumulator": lin, "LearningRate": lr},
        oracle, attrs={"l1": 0.0, "l2": 0.0, "lr_power": -0.5},
        outputs={"ParamOut": 1, "SquaredAccumOut": 1, "LinearAccumOut": 1},
        tol=1e-4,
    )


# ---- sequence / RNN ops ----------------------------------------------------


def _lens(*vals):
    return np.asarray(vals, np.int32)


@case("sequence_mask")
def _sequence_mask():
    return OpTest(
        "sequence_mask", {"X": _lens(2, 4, 0)},
        lambda ins, a: {"Y": [(np.arange(5)[None, :] < ins["X"][0][:, None]).astype(np.int64)]},
        attrs={"maxlen": 5, "out_dtype": np.dtype("int64")}, outputs={"Y": 1},
    )


def _seq_x(rng=None):
    rng = rng or R(619)
    return _mix(rng, 3, 4, 2), _lens(2, 4, 1)


@case("sequence_pool")
def _sequence_pool_avg():
    x, ln = _seq_x()

    def oracle(ins, a):
        xx, ll = ins["X"][0], ins["Length"][0]
        out = np.stack([xx[i, :ll[i]].mean(0) if ll[i] else xx[i, :1].sum(0) * 0
                        for i in range(3)])
        return {"Out": [f32(out)]}

    return OpTest(
        "sequence_pool", {"X": x, "Length": ln}, oracle,
        attrs={"pooltype": "AVERAGE"}, grad=("X",),
    )


@case("sequence_pool")
def _sequence_pool_max():
    x, ln = _seq_x(R(621))

    def oracle(ins, a):
        xx, ll = ins["X"][0], ins["Length"][0]
        out = np.stack([xx[i, :max(ll[i], 1)].max(0) for i in range(3)])
        return {"Out": [f32(out)]}

    return OpTest(
        "sequence_pool", {"X": x, "Length": ln}, oracle,
        attrs={"pooltype": "MAX"}, outputs={"Out": 1, "MaxIndex": 1}, grad=("X",),
    )


@case("sequence_pool")
def _sequence_pool_last():
    x, ln = _seq_x(R(623))

    def oracle(ins, a):
        xx, ll = ins["X"][0], ins["Length"][0]
        out = np.stack([xx[i, max(ll[i] - 1, 0)] for i in range(3)])
        return {"Out": [f32(out)]}

    return OpTest(
        "sequence_pool", {"X": x, "Length": ln}, oracle,
        attrs={"pooltype": "LAST"}, grad=("X",),
    )


@case("sequence_softmax")
def _sequence_softmax():
    rng = R(627)
    x = _mix(rng, 2, 4)
    ln = _lens(3, 4)

    def oracle(ins, a):
        xx, ll = ins["X"][0], ins["Length"][0]
        out = np.zeros_like(xx)
        for i in range(2):
            out[i, :ll[i]] = _softmax(xx[i, :ll[i]])
        return {"Out": [f32(out)]}

    return OpTest(
        "sequence_softmax", {"X": x, "Length": ln}, oracle, grad=("X",),
    )


@case("sequence_reverse")
def _sequence_reverse():
    x, ln = _seq_x(R(631))

    def oracle(ins, a):
        xx, ll = ins["X"][0].copy(), ins["Length"][0]
        out = xx.copy()
        for i in range(3):
            out[i, :ll[i]] = xx[i, :ll[i]][::-1]
        return {"Y": [out]}

    return OpTest(
        "sequence_reverse", {"X": x, "Length": ln}, oracle,
        outputs={"Y": 1}, grad=("X",),
    )


@case("sequence_expand")
def _sequence_expand():
    rng = R(641)
    x, y = _mix(rng, 3, 2), _mix(rng, 3, 4, 5)
    return OpTest(
        "sequence_expand", {"X": x, "Y": y},
        lambda ins, a: {"Out": [np.broadcast_to(ins["X"][0][:, None, :], (3, 4, 2)).copy()]},
        grad=("X",),
    )


@case("sequence_expand_as")
def _sequence_expand_as():
    rng = R(643)
    x, y = _mix(rng, 3, 2), _mix(rng, 3, 5, 1)
    return OpTest(
        "sequence_expand_as", {"X": x, "Y": y},
        lambda ins, a: {"Out": [np.broadcast_to(ins["X"][0][:, None, :], (3, 5, 2)).copy()]},
        grad=("X",),
    )


@case("sequence_conv")
def _sequence_conv():
    rng = R(647)
    x = _mix(rng, 2, 5, 3)
    w = _mix(rng, 9, 4) * 0.3

    def oracle(ins, a):
        xx, ww = ins["X"][0], ins["Filter"][0]
        xp = np.pad(xx, [(0, 0), (1, 1), (0, 0)])
        ctx = np.concatenate([xp[:, j:j + 5] for j in range(3)], axis=-1)
        return {"Out": [f32(np.einsum("btc,cf->btf", ctx, ww))]}

    return OpTest(
        "sequence_conv", {"X": x, "Filter": w}, oracle,
        attrs={"contextLength": 3, "contextStart": -1},
        grad=("X", "Filter"), tol=1e-4,
    )


@case("sequence_pad")
def _sequence_pad():
    x, ln = _seq_x(R(653))
    return OpTest(
        "sequence_pad", {"X": x, "Length": ln},
        lambda ins, a: {"Out": [ins["X"][0]], "Length": [ins["Length"][0]]},
        outputs={"Out": 1, "Length": 1},
    )


@case("sequence_unpad")
def _sequence_unpad():
    x, ln = _seq_x(R(659))

    def oracle(ins, a):
        xx, ll = ins["X"][0].copy(), ins["Length"][0]
        for i in range(3):
            xx[i, ll[i]:] = 0
        return {"Out": [xx]}

    return OpTest("sequence_unpad", {"X": x, "Length": ln}, oracle, grad=("X",))


@case("edit_distance")
def _edit_distance():
    hyp = np.asarray([[1, 2, 3, 0], [4, 4, 4, 4]], np.int64)
    ref = np.asarray([[1, 3, 3], [4, 5, 6]], np.int64)
    hlen = _lens(3, 4)
    rlen = _lens(3, 3)

    # dist(123, 133)=1; dist(4444, 456)=3
    def oracle(ins, a):
        return {"Out": [f32([[1.0], [3.0]])]}

    return OpTest(
        "edit_distance",
        {"Hyps": hyp, "Refs": ref, "HypsLength": hlen, "RefsLength": rlen},
        oracle, attrs={"normalized": False},
        outputs={"Out": 1, "SequenceNum": 1},
    )


def _np_lstm(x, w, bias, lens):
    b, t, h4 = x.shape
    h = h4 // 4
    sig = lambda z: 1 / (1 + np.exp(-z))
    hp = np.zeros((b, h), np.float32)
    cp = np.zeros((b, h), np.float32)
    hs = np.zeros((b, t, h), np.float32)
    cs = np.zeros((b, t, h), np.float32)
    for i in range(t):
        g = x[:, i] + hp @ w + bias.reshape(-1)
        c_t, i_t, f_t, o_t = np.split(g, 4, -1)
        c = np.tanh(c_t) * sig(i_t) + cp * sig(f_t)
        hh = sig(o_t) * np.tanh(c)
        keep = (i < lens)[:, None]
        hh = np.where(keep, hh, hp)
        c = np.where(keep, c, cp)
        hs[:, i], cs[:, i] = hh, c
        hp, cp = hh, c
    return f32(hs), f32(cs)


@case("lstm")
def _lstm():
    rng = R(661)
    b, t, h = 2, 3, 4
    x = _mix(rng, b, t, 4 * h) * 0.5
    w = _mix(rng, h, 4 * h) * 0.3
    bias = _mix(rng, 1, 4 * h) * 0.1
    lens = _lens(2, 3)

    def oracle(ins, a):
        hs, cs = _np_lstm(ins["Input"][0], ins["Weight"][0], ins["Bias"][0],
                          ins["Length"][0])
        return {"Hidden": [hs], "Cell": [cs]}

    return OpTest(
        "lstm", {"Input": x, "Weight": w, "Bias": bias, "Length": lens},
        oracle, outputs={"Hidden": 1, "Cell": 1},
        grad=("Input", "Weight"), tol=1e-4, grad_tol=2e-2,
    )


def _np_gru(x, w, bias, lens, origin=False):
    b, t, h3 = x.shape
    h = h3 // 3
    sig = lambda z: 1 / (1 + np.exp(-z))
    hp = np.zeros((b, h), np.float32)
    hs = np.zeros((b, t, h), np.float32)
    for i in range(t):
        g_ur = x[:, i, :2 * h] + hp @ w[:, :2 * h] + bias.reshape(-1)[:2 * h]
        u, r = sig(g_ur[:, :h]), sig(g_ur[:, h:])
        cand = np.tanh(x[:, i, 2 * h:] + (r * hp) @ w[:, 2 * h:] + bias.reshape(-1)[2 * h:])
        hh = u * hp + (1 - u) * cand if origin else (1 - u) * hp + u * cand
        keep = (i < lens)[:, None]
        hh = np.where(keep, hh, hp)
        hs[:, i] = hh
        hp = hh
    return f32(hs)


@case("gru")
def _gru():
    rng = R(673)
    b, t, h = 2, 3, 4
    x = _mix(rng, b, t, 3 * h) * 0.5
    w = _mix(rng, h, 3 * h) * 0.3
    bias = _mix(rng, 1, 3 * h) * 0.1
    lens = _lens(2, 3)

    def oracle(ins, a):
        return {"Hidden": [_np_gru(ins["Input"][0], ins["Weight"][0],
                                   ins["Bias"][0], ins["Length"][0])]}

    return OpTest(
        "gru", {"Input": x, "Weight": w, "Bias": bias, "Length": lens},
        oracle, outputs={"Hidden": 1},
        grad=("Input", "Weight"), tol=1e-4, grad_tol=2e-2,
    )


@case("linear_chain_crf")
def _crf():
    rng = R(677)
    b, t, d = 2, 4, 3
    em = _mix(rng, b, t, d)
    trans = _mix(rng, d + 2, d) * 0.5
    label = rng.randint(0, d, (b, t)).astype(np.int64)
    lens = _lens(3, 4)

    def oracle(ins, a):
        e, tr_all, lbl, ll = (ins["Emission"][0], ins["Transition"][0],
                              ins["Label"][0], ins["Length"][0])
        start, stop, tr = tr_all[0], tr_all[1], tr_all[2:]
        out = np.zeros((b, 1), np.float32)
        import itertools

        for i in range(b):
            n = ll[i]
            paths = []
            for path in itertools.product(range(d), repeat=int(n)):
                s = start[path[0]] + stop[path[-1]]
                s += sum(e[i, j, path[j]] for j in range(n))
                s += sum(tr[path[j], path[j + 1]] for j in range(n - 1))
                paths.append(s)
            logz = np.log(np.sum(np.exp(np.asarray(paths))))
            g = start[lbl[i, 0]] + stop[lbl[i, n - 1]]
            g += sum(e[i, j, lbl[i, j]] for j in range(n))
            g += sum(tr[lbl[i, j], lbl[i, j + 1]] for j in range(n - 1))
            out[i, 0] = logz - g
        return {"LogLikelihood": [out]}

    return OpTest(
        "linear_chain_crf",
        {"Emission": em, "Transition": trans, "Label": label, "Length": lens},
        oracle, outputs={"LogLikelihood": 1},
        grad=("Emission", "Transition"), tol=1e-4, grad_tol=2e-2,
    )


@case("crf_decoding")
def _crf_decoding():
    rng = R(683)
    b, t, d = 2, 3, 3
    em = _mix(rng, b, t, d)
    trans = _mix(rng, d + 2, d) * 0.5
    lens = _lens(2, 3)

    def oracle(ins, a):
        e, tr_all, ll = ins["Emission"][0], ins["Transition"][0], ins["Length"][0]
        start, stop, tr = tr_all[0], tr_all[1], tr_all[2:]
        import itertools

        out = np.zeros((b, t), np.int64)
        for i in range(b):
            n = ll[i]
            best, best_s = None, -np.inf
            for path in itertools.product(range(d), repeat=int(n)):
                s = start[path[0]] + stop[path[-1]]
                s += sum(e[i, j, path[j]] for j in range(n))
                s += sum(tr[path[j], path[j + 1]] for j in range(n - 1))
                if s > best_s:
                    best, best_s = path, s
            out[i, :n] = best
        return {"ViterbiPath": [out]}

    return OpTest(
        "crf_decoding",
        {"Emission": em, "Transition": trans, "Length": lens},
        oracle, outputs={"ViterbiPath": 1},
    )


@case("warpctc")
def _warpctc():
    rng = R(691)
    b, t, c, l = 2, 5, 4, 2
    logits = _mix(rng, b, t, c)
    label = rng.randint(1, c, (b, l)).astype(np.int32)
    tlen = _lens(5, 4)
    llen = _lens(2, 1)

    def oracle(ins, a):
        import itertools

        lg, lb = ins["Logits"][0], ins["Label"][0]
        tl, ll = ins["LogitsLength"][0], ins["LabelLength"][0]
        lp = np.log(_softmax(lg))
        out = np.zeros((b, 1), np.float32)
        for i in range(b):
            n, m = int(tl[i]), int(ll[i])
            target = list(lb[i, :m])
            total = -np.inf
            # brute force: all alignments of length n that collapse to target
            for ali in itertools.product(range(c), repeat=n):
                col = []
                prev = None
                for s in ali:
                    if s != 0 and s != prev:
                        col.append(s)
                    prev = s
                if col == target:
                    sc = sum(lp[i, j, ali[j]] for j in range(n))
                    total = np.logaddexp(total, sc)
            out[i, 0] = -total
        return {"Loss": [out]}

    return OpTest(
        "warpctc",
        {"Logits": logits, "Label": label, "LogitsLength": tlen, "LabelLength": llen},
        oracle, attrs={"blank": 0}, outputs={"Loss": 1},
        grad=("Logits",), tol=1e-4, grad_tol=2e-2,
    )


@case("beam_search")
def _beam_search():
    # B=1, W=2, V=4: hand-checked one step
    pre_ids = np.asarray([[1], [2]], np.int64)
    pre_scores = f32([[-0.5], [-1.0]])
    scores = f32([[-1.0, -2.0, -0.1, -3.0], [-0.2, -0.4, -5.0, -0.6]])

    def oracle(ins, a):
        # candidates: beam0: -0.5 + scores[0], beam1: -1.0 + scores[1]
        # beam0: [-1.5, -2.5, -0.6, -3.5]; beam1: [-1.2, -1.4, -6.0, -1.6]
        # top2 = -0.6 (b0, tok2), -1.2 (b1, tok0)
        return {
            "selected_ids": [np.asarray([[2], [0]], np.int64)],
            "selected_scores": [f32([[-0.6], [-1.2]])],
            "parent_idx": [np.asarray([0, 1], np.int32)],
        }

    return OpTest(
        "beam_search",
        {"pre_ids": pre_ids, "pre_scores": pre_scores, "scores": scores},
        oracle, attrs={"beam_size": 2, "end_id": 3},
        outputs={"selected_ids": 1, "selected_scores": 1, "parent_idx": 1},
    )


@case("cos_sim")
def _cos_sim():
    rng = R(761)
    x, y = _mix(rng, 4, 6), _mix(rng, 4, 6)

    def oracle(ins, a):
        xx, yy = ins["X"][0], ins["Y"][0]
        xn = np.linalg.norm(xx, axis=1, keepdims=True)
        yn = np.linalg.norm(yy, axis=1, keepdims=True)
        dot_ = (xx * yy).sum(1, keepdims=True)
        return {"Out": [f32(dot_ / (xn * yn))], "XNorm": [f32(xn)],
                "YNorm": [f32(yn)]}

    return OpTest(
        "cos_sim", {"X": x, "Y": y}, oracle,
        outputs={"Out": 1, "XNorm": 1, "YNorm": 1}, grad=("X", "Y"),
    )


# ---- detection ops ---------------------------------------------------------


def _np_iou(x, y):
    ax = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    ay = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    out = np.zeros((x.shape[0], y.shape[0]), np.float32)
    for i in range(x.shape[0]):
        for j in range(y.shape[0]):
            iw = min(x[i, 2], y[j, 2]) - max(x[i, 0], y[j, 0])
            ih = min(x[i, 3], y[j, 3]) - max(x[i, 1], y[j, 1])
            inter = max(iw, 0) * max(ih, 0)
            u = ax[i] + ay[j] - inter
            out[i, j] = inter / u if u > 0 else 0.0
    return out


def _boxes(rng, n):
    xy = rng.rand(n, 2).astype(np.float32)
    wh = rng.rand(n, 2).astype(np.float32) * 0.5 + 0.05
    return np.concatenate([xy, xy + wh], 1)


@case("iou_similarity")
def _iou_sim():
    rng = R(741)
    return OpTest(
        "iou_similarity", {"X": _boxes(rng, 5), "Y": _boxes(rng, 3)},
        lambda ins, a: {"Out": [_np_iou(ins["X"][0], ins["Y"][0])]},
        tol=1e-5,
    )


@case("box_coder")
def _box_coder_roundtrip():
    rng = R(743)
    prior = _boxes(rng, 4)
    target = _boxes(rng, 3)
    var = np.asarray([0.1, 0.1, 0.2, 0.2], np.float32)

    def oracle(ins, a):
        p, t = ins["PriorBox"][0], ins["TargetBox"][0]
        pw = p[:, 2] - p[:, 0]; ph = p[:, 3] - p[:, 1]
        pcx = p[:, 0] + pw / 2; pcy = p[:, 1] + ph / 2
        tw = t[:, 2] - t[:, 0]; th = t[:, 3] - t[:, 1]
        tcx = t[:, 0] + tw / 2; tcy = t[:, 1] + th / 2
        out = np.zeros((t.shape[0], p.shape[0], 4), np.float32)
        for i in range(t.shape[0]):
            for j in range(p.shape[0]):
                out[i, j] = [
                    (tcx[i] - pcx[j]) / pw[j] / var[0],
                    (tcy[i] - pcy[j]) / ph[j] / var[1],
                    np.log(tw[i] / pw[j]) / var[2],
                    np.log(th[i] / ph[j]) / var[3],
                ]
        return {"OutputBox": [out]}

    return OpTest(
        "box_coder", {"PriorBox": prior, "TargetBox": target},
        oracle, attrs={"code_type": "encode_center_size",
                       "box_normalized": True,
                       "variance": [0.1, 0.1, 0.2, 0.2]},
        outputs={"OutputBox": 1}, tol=1e-4,
    )


@case("box_coder")
def _box_coder_decode_axis1():
    rng = R(769)
    prior = _boxes(rng, 3)      # aligns with tb dim 0 (axis=1)
    deltas = f32(rng.randn(3, 2, 4) * 0.1)

    def oracle(ins, a):
        p, t = ins["PriorBox"][0], ins["TargetBox"][0]
        pw = p[:, 2] - p[:, 0]; ph = p[:, 3] - p[:, 1]
        pcx = p[:, 0] + pw / 2; pcy = p[:, 1] + ph / 2
        out = np.zeros_like(t)
        for i in range(t.shape[0]):
            for j in range(t.shape[1]):
                d = t[i, j]
                cx = d[0] * pw[i] + pcx[i]
                cy = d[1] * ph[i] + pcy[i]
                w = np.exp(d[2]) * pw[i]
                h = np.exp(d[3]) * ph[i]
                out[i, j] = [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]
        return {"OutputBox": [f32(out)]}

    return OpTest(
        "box_coder", {"PriorBox": prior, "TargetBox": deltas},
        oracle, attrs={"code_type": "decode_center_size",
                       "box_normalized": True, "axis": 1},
        outputs={"OutputBox": 1}, tol=1e-4,
    )


@case("prior_box")
def _prior_box():
    rng = R(747)
    feat = f32(rng.rand(1, 8, 2, 3))
    img = f32(rng.rand(1, 3, 64, 96))

    def oracle(ins, a):
        h, w, ih, iw = 2, 3, 64, 96
        step_h, step_w = ih / h, iw / w
        shapes = [(20.0, 20.0), (20.0 * np.sqrt(2.0), 20.0 / np.sqrt(2.0)),
                  (np.sqrt(20.0 * 40.0), np.sqrt(20.0 * 40.0))]
        boxes = np.zeros((h, w, 3, 4), np.float32)
        for yy in range(h):
            for xx in range(w):
                cx = (xx + 0.5) * step_w
                cy = (yy + 0.5) * step_h
                for k, (bw, bh) in enumerate(shapes):
                    boxes[yy, xx, k] = [(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                                        (cx + bw / 2) / iw, (cy + bh / 2) / ih]
        var = np.broadcast_to(
            np.asarray([0.1, 0.1, 0.2, 0.2], np.float32), boxes.shape
        ).copy()
        return {"Boxes": [boxes], "Variances": [var]}

    return OpTest(
        "prior_box", {"Input": feat, "Image": img}, oracle,
        attrs={"min_sizes": [20.0], "max_sizes": [40.0],
               "aspect_ratios": [2.0], "flip": False,
               "variances": [0.1, 0.1, 0.2, 0.2]},
        outputs={"Boxes": 1, "Variances": 1}, tol=1e-4,
    )


@case("yolo_box")
def _yolo_box():
    rng = R(751)
    n, p_, cls, h, w = 1, 2, 3, 2, 2
    x = f32(rng.randn(n, p_ * (5 + cls), h, w) * 0.5)
    img = np.asarray([[64, 96]], np.int32)

    def oracle(ins, a):
        sig = lambda z: 1 / (1 + np.exp(-z))
        xx = ins["X"][0].reshape(n, p_, 5 + cls, h, w)
        anchors = [10, 14, 23, 27]
        boxes = np.zeros((n, p_, h, w, 4), np.float32)
        scores = np.zeros((n, p_, h, w, cls), np.float32)
        for pi in range(p_):
            for yy in range(h):
                for xc in range(w):
                    t = xx[0, pi, :, yy, xc]
                    bx = (sig(t[0]) + xc) / w
                    by = (sig(t[1]) + yy) / h
                    bw = np.exp(t[2]) * anchors[2 * pi] / (32.0 * w)
                    bh = np.exp(t[3]) * anchors[2 * pi + 1] / (32.0 * h)
                    conf = sig(t[4])
                    b = [np.clip((bx - bw / 2) * 96, 0, 95),
                         np.clip((by - bh / 2) * 64, 0, 63),
                         np.clip((bx + bw / 2) * 96, 0, 95),
                         np.clip((by + bh / 2) * 64, 0, 63)]
                    if conf > 0.5:
                        boxes[0, pi, yy, xc] = b
                        scores[0, pi, yy, xc] = sig(t[5:]) * conf
        return {"Boxes": [boxes.reshape(n, -1, 4)],
                "Scores": [scores.reshape(n, -1, cls)]}

    return OpTest(
        "yolo_box", {"X": x, "ImgSize": img}, oracle,
        attrs={"anchors": [10, 14, 23, 27], "class_num": cls,
               "conf_thresh": 0.5, "downsample_ratio": 32},
        outputs={"Boxes": 1, "Scores": 1}, tol=1e-4,
    )


@case("roi_align")
def _roi_align():
    rng = R(757)
    x = f32(rng.rand(2, 3, 8, 8))
    rois = f32([[0.0, 0.0, 4.0, 4.0], [2.0, 2.0, 6.0, 6.0]])
    bidx = np.asarray([0, 1], np.int32)

    def oracle(ins, a):
        xx, rr = ins["X"][0], ins["ROIs"][0]
        ph = pw = 2
        ratio = 2
        out = np.zeros((2, 3, ph, pw), np.float32)

        def bil(img, yy, xx_):
            yy = np.clip(yy, 0, 7); xx_ = np.clip(xx_, 0, 7)
            y0, x0 = int(np.floor(yy)), int(np.floor(xx_))
            y1, x1 = min(y0 + 1, 7), min(x0 + 1, 7)
            ly, lx = yy - y0, xx_ - x0
            return (img[:, y0, x0] * (1 - ly) * (1 - lx) +
                    img[:, y0, x1] * (1 - ly) * lx +
                    img[:, y1, x0] * ly * (1 - lx) +
                    img[:, y1, x1] * ly * lx)

        for ri, (roi, b) in enumerate(zip(rr, [0, 1])):
            rw = max(roi[2] - roi[0], 1.0); rh = max(roi[3] - roi[1], 1.0)
            bw, bh = rw / pw, rh / ph
            for i in range(ph):
                for j in range(pw):
                    acc = np.zeros(3, np.float32)
                    for si in range(ratio):
                        for sj in range(ratio):
                            yy = roi[1] + (i + (si + 0.5) / ratio) * bh
                            xx_ = roi[0] + (j + (sj + 0.5) / ratio) * bw
                            acc += bil(xx[b], yy, xx_)
                    out[ri, :, i, j] = acc / (ratio * ratio)
        return {"Out": [out]}

    return OpTest(
        "roi_align", {"X": x, "ROIs": rois, "BatchIndex": bidx}, oracle,
        attrs={"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0,
               "sampling_ratio": 2},
        grad=("X",), tol=1e-4, grad_tol=2e-2,
    )


# ---- fake quantization -----------------------------------------------------


def _np_qdq(x, scale, bits=8):
    qmax = 2 ** (bits - 1) - 1
    s = np.maximum(scale, 1e-8)
    return np.clip(np.round(x / s * qmax), -qmax, qmax) * s / qmax


@case("fake_quantize_dequantize_abs_max")
def _fqdq_absmax():
    x = _mix(R(719), 3, 4)

    def oracle(ins, a):
        s = np.abs(ins["X"][0]).max()
        return {"Out": [f32(_np_qdq(ins["X"][0], s))], "OutScale": [f32([s])]}

    return OpTest(
        "fake_quantize_dequantize_abs_max", {"X": x}, oracle,
        attrs={"bit_length": 8}, outputs={"Out": 1, "OutScale": 1}, tol=1e-5,
    )


@case("fake_quantize_dequantize_moving_average_abs_max")
def _fqdq_ema():
    rng = R(727)
    x = _mix(rng, 3, 4)
    accum, state = f32([0.7]), f32([1.0])

    def oracle(ins, a):
        na = 0.9 * ins["InAccum"][0][0] + np.abs(ins["X"][0]).max()
        ns = 0.9 * ins["InState"][0][0] + 1.0
        s = na / ns
        return {"Out": [f32(_np_qdq(ins["X"][0], s))],
                "OutAccum": [f32([na])], "OutState": [f32([ns])],
                "OutScale": [f32([s])]}

    return OpTest(
        "fake_quantize_dequantize_moving_average_abs_max",
        {"X": x, "InAccum": accum, "InState": state}, oracle,
        attrs={"bit_length": 8, "moving_rate": 0.9},
        outputs={"Out": 1, "OutAccum": 1, "OutState": 1, "OutScale": 1},
        tol=1e-5,
    )


@case("fake_quant_dequant_fixed_scale")
def _fqdq_fixed():
    x = _mix(R(733), 3, 4)
    return OpTest(
        "fake_quant_dequant_fixed_scale", {"X": x},
        lambda ins, a: {"Out": [f32(_np_qdq(ins["X"][0], 1.5))]},
        attrs={"bit_length": 8, "scale": 1.5}, tol=1e-5,
    )


# ---- breadth ops (vision_ops.py / misc_ops.py) ----------------------------

unary("selu", lambda x, a: np.where(
    x > 0, x, 1.6732632423543772 * (np.exp(x) - 1.0)) * 1.0507009873554805)
unary("brelu", lambda x, a: np.clip(x, 1.0, 3.0),
      attrs={"t_min": 1.0, "t_max": 3.0}, inp=_pos, grad=False)
unary("soft_relu", lambda x, a: np.log1p(np.exp(np.clip(x, -40.0, 40.0))))
unary("stanh", lambda x, a: 1.7159 * np.tanh(0.67 * x))


@case("multiplex")
def _multiplex():
    rng = R(61)
    xs = [_mix(rng, 4, 3), _mix(rng, 4, 3), _mix(rng, 4, 3)]
    ids = np.asarray([[2], [0], [1], [0]], np.int32)

    def oracle(ins, a):
        stacked = np.stack(ins["X"])
        sel = ins["Ids"][0].reshape(-1)
        return {"Out": [stacked[sel, np.arange(4)]]}

    return OpTest("multiplex", {"X": xs, "Ids": ids}, oracle, grad=("X",))


@case("mean_iou")
def _mean_iou():
    pred = np.asarray([0, 1, 1, 2, 2, 2], np.int32)
    lab = np.asarray([0, 1, 2, 2, 2, 1], np.int32)

    def oracle(ins, a):
        nc = 3
        inter = np.zeros(nc)
        union = np.zeros(nc)
        for c in range(nc):
            p, l = pred == c, lab == c
            inter[c] = (p & l).sum()
            union[c] = (p | l).sum()
        iou = np.where(union > 0, inter / np.maximum(union, 1), 0)
        return {"OutMeanIou": [np.float32(iou[union > 0].mean())]}

    return OpTest(
        "mean_iou", {"Predictions": pred, "Labels": lab}, oracle,
        attrs={"num_classes": 3},
        outputs={"OutMeanIou": 1, "OutWrong": 1, "OutCorrect": 1},
    )


@case("pixel_shuffle")
def _pixel_shuffle():
    rng = R(62)
    x = _mix(rng, 2, 8, 3, 3)

    def oracle(ins, a):
        n, c, h, w = ins["X"][0].shape
        r, oc = 2, c // 4
        t = ins["X"][0].reshape(n, oc, r, r, h, w).transpose(0, 1, 4, 2, 5, 3)
        return {"Out": [t.reshape(n, oc, h * r, w * r)]}

    return OpTest("pixel_shuffle", {"X": x}, oracle,
                  attrs={"upscale_factor": 2}, grad=("X",))


@case("space_to_depth")
def _space_to_depth():
    rng = R(63)
    x = _mix(rng, 2, 3, 4, 4)

    def oracle(ins, a):
        n, c, h, w = ins["X"][0].shape
        bs = 2
        t = ins["X"][0].reshape(n, c, h // bs, bs, w // bs, bs)
        t = t.transpose(0, 3, 5, 1, 2, 4)
        return {"Out": [t.reshape(n, c * bs * bs, h // bs, w // bs)]}

    return OpTest("space_to_depth", {"X": x}, oracle,
                  attrs={"blocksize": 2}, grad=("X",))


@case("shuffle_channel")
def _shuffle_channel():
    rng = R(64)
    x = _mix(rng, 2, 6, 2, 2)

    def oracle(ins, a):
        n, c, h, w = ins["X"][0].shape
        g = 3
        return {"Out": [ins["X"][0].reshape(n, g, c // g, h, w)
                        .swapaxes(1, 2).reshape(n, c, h, w)]}

    return OpTest("shuffle_channel", {"X": x}, oracle,
                  attrs={"group": 3}, grad=("X",))


@case("temporal_shift")
def _temporal_shift():
    rng = R(65)
    x = _mix(rng, 4, 8, 2, 2)  # N*T with T=2

    def oracle(ins, a):
        t = 2
        nt, c, h, w = ins["X"][0].shape
        x5 = ins["X"][0].reshape(nt // t, t, c, h, w)
        c1, c2 = c // 4, c // 2
        out = np.zeros_like(x5)
        out[:, :-1, :c1] = x5[:, 1:, :c1]
        out[:, 1:, c1:c2] = x5[:, :-1, c1:c2]
        out[:, :, c2:] = x5[:, :, c2:]
        return {"Out": [out.reshape(nt, c, h, w)]}

    return OpTest("temporal_shift", {"X": x}, oracle,
                  attrs={"seg_num": 2, "shift_ratio": 0.25}, grad=("X",))


@case("row_conv")
def _row_conv():
    rng = R(66)
    x = _mix(rng, 2, 5, 3)
    f = _mix(rng, 3, 3)

    def oracle(ins, a):
        xx, ff = ins["X"][0], ins["Filter"][0]
        pad = np.pad(xx, [(0, 0), (0, ff.shape[0] - 1), (0, 0)])
        out = np.zeros_like(xx)
        for k in range(ff.shape[0]):
            out += pad[:, k : k + xx.shape[1]] * ff[k][None, None, :]
        return {"Out": [out]}

    return OpTest("row_conv", {"X": x, "Filter": f}, oracle,
                  grad=("X", "Filter"))


@case("bilinear_tensor_product")
def _bilinear_tensor_product():
    rng = R(67)
    x, y = _mix(rng, 3, 4), _mix(rng, 3, 5)
    w = _mix(rng, 2, 4, 5)
    b = _mix(rng, 1, 2)

    def oracle(ins, a):
        out = np.einsum("bi,kij,bj->bk", ins["X"][0], ins["Weight"][0],
                        ins["Y"][0]) + ins["Bias"][0]
        return {"Out": [out.astype(np.float32)]}

    return OpTest(
        "bilinear_tensor_product",
        {"X": x, "Y": y, "Weight": w, "Bias": b}, oracle,
        grad=("X", "Y", "Weight"),
    )


@case("lrn")
def _lrn():
    rng = R(68)
    x = _mix(rng, 2, 6, 3, 3)

    def oracle(ins, a):
        xx = ins["X"][0]
        n, k, alpha, beta = 5, 1.0, 1e-4, 0.75
        sq = xx * xx
        half = n // 2
        padded = np.pad(sq, [(0, 0), (half, n - 1 - half), (0, 0), (0, 0)])
        win = sum(padded[:, i : i + xx.shape[1]] for i in range(n))
        return {"Out": [(xx / (k + alpha * win) ** beta).astype(np.float32)]}

    return OpTest("lrn", {"X": x}, oracle,
                  outputs={"Out": 1, "MidOut": 1}, grad=("X",))


@case("pool3d")
def _pool3d():
    rng = R(69)
    x = _mix(rng, 1, 2, 4, 4, 4)

    def oracle(ins, a):
        xx = ins["X"][0]
        n, c, d, h, w = xx.shape
        out = xx.reshape(n, c, d // 2, 2, h // 2, 2, w // 2, 2).max(
            axis=(3, 5, 7))
        return {"Out": [out]}

    return OpTest("pool3d", {"X": x}, oracle,
                  attrs={"pooling_type": "max", "ksize": [2, 2, 2],
                         "strides": [2, 2, 2]}, grad=("X",))


@case("unfold")
def _unfold():
    rng = R(70)
    x = _mix(rng, 1, 2, 4, 4)

    def oracle(ins, a):
        xx = ins["X"][0]
        n, c, h, w = xx.shape
        cols = []
        for i in range(h - 1):
            for j in range(w - 1):
                cols.append(xx[:, :, i : i + 2, j : j + 2].reshape(n, -1))
        return {"Y": [np.stack(cols, axis=-1)]}

    return OpTest("unfold", {"X": x}, oracle,
                  attrs={"kernel_sizes": [2, 2]},
                  outputs={"Y": 1}, grad=("X",))


@case("im2sequence")
def _im2sequence():
    rng = R(71)
    x = _mix(rng, 1, 2, 3, 3)

    def oracle(ins, a):
        xx = ins["X"][0]
        n, c, h, w = xx.shape
        rows = []
        for i in range(h - 1):
            for j in range(w - 1):
                rows.append(xx[:, :, i : i + 2, j : j + 2].reshape(n, -1))
        return {"Out": [np.stack(rows, axis=1)]}

    return OpTest("im2sequence", {"X": x}, oracle,
                  attrs={"kernels": [2, 2]}, grad=("X",))


@case("sequence_enumerate")
def _sequence_enumerate():
    x = np.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
    ln = np.asarray([3, 4], np.int32)

    def oracle(ins, a):
        out = np.zeros((2, 4, 2), np.int32)
        for b in range(2):
            for t in range(4):
                for k in range(2):
                    out[b, t, k] = x[b, t + k] if t + k < ln[b] else 0
        return {"Out": [out]}

    return OpTest("sequence_enumerate", {"X": x, "Length": ln}, oracle,
                  attrs={"win_size": 2, "pad_value": 0})


@case("sequence_slice")
def _sequence_slice():
    rng = R(72)
    x = _mix(rng, 2, 5, 3)
    off = np.asarray([1, 2], np.int32)
    ln = np.asarray([3, 2], np.int32)

    def oracle(ins, a):
        out = np.zeros_like(x)
        for b in range(2):
            out[b, : ln[b]] = x[b, off[b] : off[b] + ln[b]]
        return {"Out": [out]}

    return OpTest("sequence_slice", {"X": x, "Offset": off, "Length": ln},
                  oracle, outputs={"Out": 1, "OutLength": 1}, grad=("X",))


@case("sequence_reshape")
def _sequence_reshape():
    rng = R(73)
    x = _mix(rng, 2, 4, 6)

    def oracle(ins, a):
        return {"Out": [ins["X"][0].reshape(2, 8, 3)]}

    return OpTest("sequence_reshape", {"X": x}, oracle,
                  attrs={"new_dim": 3}, grad=("X",))


@case("sequence_scatter")
def _sequence_scatter():
    rng = R(74)
    x = _mix(rng, 2, 6)
    ids = np.asarray([[0, 2, 2], [5, 1, 0]], np.int32)
    upd = _mix(rng, 2, 3)
    ln = np.asarray([3, 2], np.int32)

    def oracle(ins, a):
        out = x.copy()
        for b in range(2):
            for s in range(3):
                if s < ln[b]:
                    out[b, ids[b, s]] += upd[b, s]
        return {"Out": [out]}

    return OpTest("sequence_scatter",
                  {"X": x, "Ids": ids, "Updates": upd, "Length": ln},
                  oracle, grad=("X",))


@case("sequence_concat")
def _sequence_concat():
    rng = R(75)
    a_ = _mix(rng, 2, 3, 2)
    b_ = _mix(rng, 2, 2, 2)
    lens = np.asarray([[2, 3], [1, 2]], np.int32)  # stacked [k, B] -> flat

    def oracle(ins, at):
        out = np.zeros((2, 5, 2), np.float32)
        newlen = np.zeros(2, np.int32)
        for b in range(2):
            pos = 0
            for x, ln in ((a_, lens[0]), (b_, lens[1])):
                out[b, pos : pos + ln[b]] = x[b, : ln[b]]
                pos += ln[b]
            newlen[b] = pos
        return {"Out": [out], "Length": [newlen]}

    return OpTest("sequence_concat",
                  {"X": [a_, b_], "Length": lens.reshape(-1)},
                  oracle, outputs={"Out": 1, "Length": 1}, grad=("X",))


@case("gather_tree")
def _gather_tree():
    # T=3, B=1, W=2 hand-traced beam backtrace
    ids = np.asarray([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
    parents = np.asarray([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)

    def oracle(ins, a):
        # final beams: w0 traces parent 1 at t2 -> ids path [1,4,5];
        # w1 traces parent 0 -> [1,3,6]
        return {"Out": [np.asarray([[[1, 1]], [[4, 3]], [[5, 6]]], np.int64)]}

    return OpTest("gather_tree", {"Ids": ids, "Parents": parents}, oracle)


unary("tanh_shrink", lambda x, a: x - np.tanh(x))


@case("diag_embed")
def _diag_embed():
    rng = R(77)
    x = _mix(rng, 2, 4)

    def oracle(ins, a):
        out = np.zeros((2, 4, 4), np.float32)
        for b in range(2):
            np.fill_diagonal(out[b], ins["X"][0][b])
        return {"Out": [out]}

    return OpTest("diag_embed", {"X": x}, oracle, grad=("X",))


@case("histogram")
def _histogram():
    x = np.asarray([0.1, 0.2, 0.55, 0.9, 0.95, 2.0], np.float32)

    def oracle(ins, a):
        return {"Out": [np.histogram(x, bins=4, range=(0, 1))[0]
                        .astype(np.int32)]}

    return OpTest("histogram", {"X": x}, oracle,
                  attrs={"bins": 4, "min": 0.0, "max": 1.0})


@case("nonzero_static")
def _nonzero_static():
    x = np.asarray([[0, 3, 0], [2, 0, 1]], np.float32)

    def oracle(ins, a):
        idx = np.argwhere(x != 0).astype(np.int32)
        pad = np.full((x.size - len(idx), 2), -1, np.int32)
        return {"Out": [np.concatenate([idx, pad])],
                "Count": [np.int32(len(idx))]}

    return OpTest("nonzero_static", {"X": x}, oracle,
                  outputs={"Out": 1, "Count": 1})


# ---------------------------------------------------------------------------
# exemptions: ops whose contract is verified elsewhere or is stochastic
# ---------------------------------------------------------------------------

EXEMPT = {
    # numerics-observability reduction (ISSUE 12): emitter checked
    # against numpy (nan/inf counts, finite max-abs/l2) in
    # tests/test_numerics.py::test_tensor_stats_emitter_matches_numpy
    "tensor_stats": "test_numerics.py",
    # collectives need a mesh + axis env; numerics are checked against
    # numpy on an 8-device virtual mesh in tests/test_collectives.py
    "c_allgather": "test_collectives.py",
    "c_allreduce_max": "test_collectives.py",
    "c_allreduce_min": "test_collectives.py",
    "c_allreduce_prod": "test_collectives.py",
    "c_allreduce_sum": "test_collectives.py",
    "c_broadcast": "test_collectives.py",
    "c_reducescatter": "test_collectives.py",
    "c_identity": "test_collectives.py",
    # comm bootstrap/sync ops are no-ops under XLA (PJRT owns streams);
    # exercised by every fleet/dryrun program in test_fleet.py
    "c_comm_init": "no-op under XLA; test_fleet.py",
    "c_comm_init_all": "no-op under XLA; test_fleet.py",
    "c_gen_nccl_id": "no-op under XLA; test_fleet.py",
    "c_sync_calc_stream": "no-op under XLA; test_fleet.py",
    "c_sync_comm_stream": "no-op under XLA; test_fleet.py",
    "c_wait_comm": "no-op under XLA; test_fleet.py",
    "c_wait_compute": "no-op under XLA; test_fleet.py",
    # side-effect ops (host print/assert callbacks): test_control_flow.py
    "print": "test_control_flow.py (passthrough + host print)",
    "assert": "test_control_flow.py (raises on false cond)",
    # control flow needs sub-block programs: tests/test_control_flow.py
    "cond": "test_control_flow.py",
    "while_loop": "test_control_flow.py",
    "recurrent": "sub-block scan; test_static_rnn_pyfunc.py (numpy oracle)",
    "py_func": "host callable in attrs; test_static_rnn_pyfunc.py",
    "select_input": "test_control_flow.py",
    # fused mega-ops have dedicated oracle suites
    "moe_ffn": "test_moe.py (numpy routing oracle, capacity, ep parity)",
    "fused_encoder_stack": "test_bert.py (vs per-layer composition)",
    "fused_decoder_stack": "test_sequence_models.py (fused NMT stack "
                           "trains + stays causal)",
    "c_dcn_grad_sync": "test_dcn.py (two-level sync parity + DGC "
                       "oracles on the (dcn, dp) mesh)",
    "c_dcn_localsgd_sync": "test_dcn.py (LocalSGD consensus oracle on "
                           "the (dcn, dp) mesh)",
    "dcn_expand_param": "test_dcn.py (outer-optimizer state expansion)",
    "tree_conv": "test_tree_conv.py (numpy eta-coefficient oracle)",
    "fused_multihead_attention": "test_flash_attention.py + test_bert.py",
    "recompute_segment": "test_meta_optimizers.py (recompute)",
    # explicit grad kernels: exercised by check_grad of their forward op
    "dropout_grad": "via dropout case's check_grad",
    "argsort_grad": "via argsort case's check_grad",
    "top_k_grad": "via top_k case's check_grad",
    "top_k_v2_grad": "via top_k_v2 case's check_grad",
    # host parameter-server bridge: needs the global table registry and
    # host-side optimizer state; covered end to end in test_ps_embedding.py
    "distributed_lookup_table": "test_ps_embedding.py",
    # detection batch 2: numpy oracles through the executor in
    # tests/test_detection2.py (static-shape NMS/assignment contracts)
    "anchor_generator": "test_detection2.py (hand oracle)",
    "density_prior_box": "test_detection2.py",
    "box_clip": "test_detection2.py (hand oracle)",
    "box_decoder_and_assign": "test_detection2.py (zero-delta oracle)",
    "multiclass_nms": "test_detection2.py (suppression + padding)",
    "matrix_nms": "test_detection2.py (decay semantics)",
    "locality_aware_nms": "test_detection2.py (merge + NMS)",
    "target_assign": "test_detection2.py (hand oracle)",
    "bipartite_match": "test_detection2.py (greedy oracle)",
    "polygon_box_transform": "test_detection2.py (hand oracle)",
    "ctc_align": "test_detection2.py (collapse oracle)",
    "ssd_loss": "test_detection2.py (end-to-end training)",
    # detection batch 3 (proposals/ROI/yolo): tests/test_detection2.py
    "generate_proposals": "test_detection2.py (shapes/clip/NMS)",
    "rpn_target_assign": "test_detection2.py (budget + exact-match deltas)",
    "retinanet_target_assign": "test_detection2.py via rpn variant",
    "collect_fpn_proposals": "test_detection2.py",
    "distribute_fpn_proposals": "test_detection2.py (restore permutation)",
    "prroi_pool": "test_detection2.py (shape/finite)",
    "psroi_pool": "test_detection2.py (shape/finite)",
    "roi_perspective_transform": "test_detection2.py (identity-quad oracle)",
    "deformable_conv": "test_detection2.py (zero-offset == conv2d)",
    "deformable_psroi_pooling": "test_detection2.py via deformable_roi_pooling",
    "yolov3_loss": "test_detection2.py (end-to-end training)",
    # vision/misc breadth ops: numpy-oracle + semantics tests through the
    # executor live in tests/test_layers_breadth.py
    "conv3d_transpose": "test_layers_breadth.py (adjoint + identity oracle)",
    "bilinear_interp": "test_layers_breadth.py (corner/align oracle)",
    "nearest_interp": "test_layers_breadth.py (integer-upscale oracle)",
    "trilinear_interp": "test_layers_breadth.py",
    "linear_interp": "test_layers_breadth.py",
    "affine_grid": "test_layers_breadth.py (identity-theta oracle)",
    "grid_sampler": "test_layers_breadth.py (identity-grid oracle)",
    "roi_pool": "test_layers_breadth.py (hand-computed ROI oracle)",
    "spectral_norm": "test_layers_breadth.py (sigma_max vs numpy svd)",
    "data_norm": "test_layers_breadth.py (accumulator-stat oracle)",
    "unique": "test_layers_breadth.py (static-shape padding contract)",
    "unique_with_counts": "test_layers_breadth.py",
    "hash": "test_layers_breadth.py (determinism/range/spread)",
    "sampling_id": "test_layers_breadth.py (distribution check)",
    "randperm": "test_api20.py (permutation property; stochastic)",
    "precision_recall": "test_layers_breadth2.py (streaming states)",
    # stochastic draws: distribution checked in test_random_ops below
    "uniform_random": "test_random_ops",
    "gaussian_random": "test_random_ops",
    "truncated_gaussian_random": "test_random_ops",
    "dpsgd": "test_random_ops (noisy update; mean drift checked)",
}


# ---------------------------------------------------------------------------
# the tests
# ---------------------------------------------------------------------------


def test_coverage():
    registered = set(registry.registered_ops())
    # registry.get() caches lazily synthesized generic "<op>_grad" specs;
    # those are the vjp of an already-covered forward op, not independent
    # kernels. Keep only grad ops with their own explicit registration
    # (they appear in EXEMPT with a justification).
    registered -= {
        n for n in registered
        if n.endswith("_grad") and n[: -len("_grad")] in registered
        and n not in EXEMPT and n not in CASES
    }
    covered = set(CASES) | set(EXEMPT)
    missing = registered - covered
    assert not missing, f"ops with neither case nor exemption: {sorted(missing)}"
    double = set(CASES) & set(EXEMPT)
    assert not double, f"ops both cased and exempted: {sorted(double)}"
    stale = covered - registered
    assert not stale, f"cases/exemptions for unregistered ops: {sorted(stale)}"


_ALL = [(op, i) for op, fns in sorted(CASES.items()) for i in range(len(fns))]


@pytest.mark.parametrize("op_type,i", _ALL, ids=[f"{o}-{i}" for o, i in _ALL])
def test_op(op_type, i):
    CASES[op_type][i]().run()


def test_random_ops():
    """Statistical checks for the stochastic creation ops + dpsgd."""
    import paddle_tpu.fluid as fluid

    def run_op(op_type, attrs, inputs=None, outputs=("Out",)):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            feed = {}
            in_names = {}
            for slot, arr in (inputs or {}).items():
                n = f"in_{slot}"
                block.create_var(name=n, shape=arr.shape, dtype=arr.dtype)
                feed[n] = arr
                in_names[slot] = [n]
            for o in outputs:
                block.create_var(name=f"out_{o}")
            block.append_op(
                type=op_type, inputs=in_names,
                outputs={o: [f"out_{o}"] for o in outputs}, attrs=attrs,
            )
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.executor.Scope()):
            exe.run(startup)
            return [
                np.asarray(v)
                for v in exe.run(main, feed=feed, fetch_list=[f"out_{o}" for o in outputs])
            ]

    (u,) = run_op(
        "uniform_random",
        {"shape": [1000], "min": -2.0, "max": 2.0, "dtype": np.dtype("float32")},
    )
    assert u.min() >= -2.0 and u.max() <= 2.0
    assert abs(u.mean()) < 0.2

    (g,) = run_op(
        "gaussian_random",
        {"shape": [2000], "mean": 1.0, "std": 2.0, "dtype": np.dtype("float32")},
    )
    assert abs(g.mean() - 1.0) < 0.2 and abs(g.std() - 2.0) < 0.3

    (t,) = run_op(
        "truncated_gaussian_random",
        {"shape": [2000], "mean": 0.0, "std": 1.0, "dtype": np.dtype("float32")},
    )
    assert np.abs(t).max() <= 2.01 and abs(t.mean()) < 0.15

    rng = R(617)
    p = f32(rng.rand(200))
    gr = f32(rng.rand(200) * 0.1)
    (po,) = run_op(
        "dpsgd",
        {"clip": 1e6, "sigma": 0.0, "batch_size": 1.0},
        inputs={"Param": p, "Grad": gr, "LearningRate": f32([0.1])},
        outputs=("ParamOut",),
    )
    np.testing.assert_allclose(po, p - 0.1 * gr, rtol=1e-5, atol=1e-5)
